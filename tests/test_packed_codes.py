"""Bit-packed sub-byte code lanes (ISSUE 16 tentpole).

Contract under test: with ``HYPERSPACE_PACKED_CODES`` on (the auto default,
riding ``HYPERSPACE_ENCODED_DEVICE``), dictionary codes BELOW int8 cross the
host→device boundary and the mesh exchange as 1/2/4-bit lanes packed into
uint32 words — while every result (join rows, index file bytes) stays
BYTE-IDENTICAL to the ``=0`` narrow fallback, in both
``HYPERSPACE_DISTRIBUTED`` ambients. The layout is pinned property-style:
pack/unpack is bijective for every dictionary within the class bound, the
null code folds into the reserved lane 0, and big-endian lane order makes
unsigned packed-word compare equal lexicographic lane compare (the
compute-on-packed soundness lemma the Pallas probe/sort kernels rely on).
Lane counts pow2-quantize so the jitted pack/unpack programs stay a bounded
compile-class set — never one shape per cardinality.
"""

import hashlib
import os

import numpy as np
import pytest

from hyperspace_tpu import IndexConfig, IndexConstants
from hyperspace_tpu.engine import HyperspaceSession, col
from hyperspace_tpu.engine import packed_codes as pc
from hyperspace_tpu.engine.table import Column, Table
from hyperspace_tpu.hyperspace import Hyperspace, enable_hyperspace
from hyperspace_tpu.telemetry import compile_log, metrics

ENV = pc.ENV_PACKED_CODES
ENV_ENC = "HYPERSPACE_ENCODED_DEVICE"

# Distinct from every other suite so mesh program shapes are this file's own.
NUM_BUCKETS = 26


def _session(tmp_path, num_buckets=NUM_BUCKETS):
    s = HyperspaceSession(warehouse=str(tmp_path))
    s.conf.set(IndexConstants.INDEX_SYSTEM_PATH, str(tmp_path / "indexes"))
    s.conf.set(IndexConstants.INDEX_NUM_BUCKETS, num_buckets)
    s.conf.set(IndexConstants.DISTRIBUTED_MIN_ROWS, 0)
    return s


def _clear_caches():
    from hyperspace_tpu.engine.physical import clear_device_memos
    from hyperspace_tpu.engine.scan_cache import (
        global_bucketed_cache,
        global_concat_cache,
        global_filtered_cache,
        global_scan_cache,
    )

    global_scan_cache().clear()
    global_concat_cache().clear()
    global_filtered_cache().clear()
    global_bucketed_cache().clear()
    clear_device_memos()
    pc.clear_packed_memos()


def _write_lowcard_pair(s, base, n, card=12, seed=7):
    """String-key fact/dim pair with `card` ≤ 15 distinct keys — the 4-bit
    packed lane class."""
    rng = np.random.RandomState(seed)
    s.write_parquet(
        {
            "sk": np.array([f"k{v:02d}" for v in rng.randint(0, card, n)]),
            "val": np.arange(n, dtype=np.int64),
        },
        os.path.join(base, "fact"),
    )
    s.write_parquet(
        {
            "dk": np.array([f"k{v:02d}" for v in rng.randint(0, card, n // 4)]),
            "w": rng.randint(0, 100, n // 4).astype(np.int64),
        },
        os.path.join(base, "dim"),
    )


def _dir_hashes(root):
    return {
        f: hashlib.sha256(open(os.path.join(root, f), "rb").read()).hexdigest()
        for f in sorted(os.listdir(root))
        if f.startswith("part-")
    }


# ---------------------------------------------------------------------------
# Width policy units
# ---------------------------------------------------------------------------


class TestWidthPolicy:
    def test_transport_bits_boundaries(self):
        # card + 1 biased values must fit: the reserved 0 eats one slot.
        assert pc.bits_for_cardinality(1) == 1
        assert pc.bits_for_cardinality(2) == 2
        assert pc.bits_for_cardinality(3) == 2
        assert pc.bits_for_cardinality(4) == 4
        assert pc.bits_for_cardinality(15) == 4
        assert pc.bits_for_cardinality(16) is None  # int8 narrow class takes it

    def test_probe_bits_reserve_pad_slot(self):
        # The compute path reserves the TOP lane value as pad: one slot fewer.
        assert pc.probe_bits_for_cardinality(2) == 2
        assert pc.probe_bits_for_cardinality(14) == 4
        assert pc.probe_bits_for_cardinality(15) is None

    def test_wire_bits(self):
        assert pc.wire_bits_for_range(2) == 1
        assert pc.wire_bits_for_range(26) == 8
        assert pc.wire_bits_for_range(65536) == 16
        assert pc.wire_bits_for_range(65537) is None

    def test_mode_parsing_auto_rides_encoded(self, monkeypatch):
        monkeypatch.delenv(ENV, raising=False)
        assert pc.packed_codes_mode() == "auto"
        monkeypatch.setenv(ENV_ENC, "1")
        assert pc.packed_codes_enabled()
        monkeypatch.setenv(ENV_ENC, "0")
        assert not pc.packed_codes_enabled()
        monkeypatch.setenv(ENV, "0")
        monkeypatch.setenv(ENV_ENC, "1")
        assert not pc.packed_codes_enabled()
        monkeypatch.setenv(ENV, "1")
        monkeypatch.setenv(ENV_ENC, "0")
        assert pc.packed_codes_enabled()

    def test_lane_count_is_word_granular_exact(self):
        """The H2D buffer is EXACT to the word: at most one word of tail
        padding, so the packed-vs-narrow wire ratio stays the intrinsic
        8/bits (pow2 padding happens device-side, never on the wire)."""
        for bits in pc.PACKED_BITS:
            lpw = pc.lanes_per_word(bits)
            for n in (1, 2, 3, 5, 31, 32, 33, 1000, 4097, 300000):
                lanes = pc.packed_lane_count(n, bits)
                assert lanes >= max(n, 1)
                assert lanes - max(n, 1) < lpw  # <= one word of tail
                assert lanes % lpw == 0
                assert pc.packed_word_count(n, bits) == lanes // lpw

    def test_device_unpack_classes_are_pow2(self, monkeypatch):
        """Two exact word counts in the same pow2 class share ONE compiled
        unpack program — the device-side zero-pad bridges exact wire buffers
        onto the bounded (bits, pow2) grid."""
        import jax.numpy as jnp

        pc._unpack_programs.clear()
        for n in (900, 1000):  # both pad to 128 words at 4 bits
            codes = np.arange(n, dtype=np.int32) % 14
            words = pc.pack_codes_host(codes, 4)
            lane = pc.unpack_codes_device(jnp.asarray(words), 4)
            assert np.array_equal(np.asarray(lane)[:n], codes.astype(np.int8))
        assert len(pc._unpack_programs) == 1


# ---------------------------------------------------------------------------
# Layout properties: bijectivity, reserved null, the order lemma
# ---------------------------------------------------------------------------


class TestLayoutProperties:
    def test_roundtrip_bijective_across_cardinalities(self):
        """Every dict size within each class bound (plus nulls) round-trips
        exactly, for a spread of lengths including the pow2 edges."""
        rng = np.random.RandomState(3)
        for card in (1, 2, 3, 4, 7, 12, 15):
            bits = pc.bits_for_cardinality(card)
            assert bits is not None
            for n in (1, 7, 32, 33, 257, 4096):
                codes = rng.randint(0, card, n).astype(np.int32)
                codes[rng.rand(n) < 0.1] = -1  # folded nulls
                words = pc.pack_codes_host(codes, bits)
                assert words.dtype == np.uint32
                assert len(words) == pc.packed_word_count(n, bits)
                back = pc.unpack_codes_host(words, n, bits)
                assert np.array_equal(back, codes), (card, n)

    def test_wide_cardinality_has_no_packed_class(self):
        # 70k distinct: past int16 even — nothing in the packed layer applies.
        assert pc.bits_for_cardinality(70_000) is None
        assert pc.probe_bits_for_cardinality(70_000) is None

    def test_null_code_is_reserved_lane_zero(self):
        words = pc.pack_codes_host(np.array([-1], np.int32), 4)
        # Big-endian: lane 0 sits in the TOP bits; biased null = 0.
        assert int(words[0]) >> 28 == 0
        words = pc.pack_codes_host(np.array([0], np.int32), 4)
        assert int(words[0]) >> 28 == 1  # biased code 0 -> lane value 1

    def test_packed_word_order_is_lane_order(self):
        """The compute lemma: unsigned word compare == lexicographic biased
        lane compare, for random lane tuples in every bits class."""
        rng = np.random.RandomState(11)
        for bits in pc.PACKED_BITS:
            lpw = pc.lanes_per_word(bits)
            hi = 1 << bits
            for _ in range(200):
                a = rng.randint(0, hi, lpw).astype(np.int64)
                b = rng.randint(0, hi, lpw).astype(np.int64)
                wa = pc.pack_codes_host((a - 1).astype(np.int32), bits)[0]
                wb = pc.pack_codes_host((b - 1).astype(np.int32), bits)[0]
                lex = int(tuple(a) > tuple(b)) - int(tuple(a) < tuple(b))
                word = int(int(wa) > int(wb)) - int(int(wa) < int(wb))
                assert lex == word, (bits, a, b)

    def test_device_unpack_matches_host(self, monkeypatch):
        import jax.numpy as jnp

        for bits in pc.PACKED_BITS:
            n = 100
            rng = np.random.RandomState(bits)
            codes = rng.randint(-1, (1 << bits) - 1, n).astype(np.int32)
            words = pc.pack_codes_host(codes, bits)
            lane = pc.unpack_codes_device(jnp.asarray(words), bits)
            assert lane.dtype == jnp.int8
            assert np.array_equal(np.asarray(lane)[:n], codes)

    def test_traced_row_pack_roundtrip(self):
        import jax.numpy as jnp

        rng = np.random.RandomState(17)
        for bits in pc.PACKED_BITS:
            lpw = pc.lanes_per_word(bits)
            mat = rng.randint(0, 1 << bits, (6, 4 * lpw))
            words = pc.pack_rows_traced(jnp.asarray(mat), bits)
            assert words.shape == (6, 4)
            back = pc.unpack_rows_traced(words, bits)
            assert np.array_equal(np.asarray(back), mat)


# ---------------------------------------------------------------------------
# Staging: packed tier bytes + memoization + identical lane values
# ---------------------------------------------------------------------------


class TestPackedStaging:
    def _lowcard_column(self, n=500, card=12, seed=5, with_nulls=False):
        rng = np.random.RandomState(seed)
        dictionary = np.sort(np.array([f"k{i:02d}" for i in range(card)]))
        codes = rng.randint(0, card, n).astype(np.int32)
        validity = None
        if with_nulls:
            validity = rng.rand(n) > 0.1
            codes = np.where(validity, codes, 0)
        c = Column("string", codes, dictionary, validity)
        c._encoded_read = True
        return c

    def test_stage_codes_takes_packed_tier(self, monkeypatch):
        monkeypatch.setenv(ENV_ENC, "1")
        monkeypatch.delenv(ENV, raising=False)
        _clear_caches()
        from hyperspace_tpu.engine.encoded_device import narrow_codes, stage_codes

        c = self._lowcard_column()
        packed0 = metrics.counter("device.encoded.bytes_packed").value
        lane = stage_codes(c, "test_packed_site")
        packed1 = metrics.counter("device.encoded.bytes_packed").value
        assert packed1 > packed0, "packed tier did not tick"
        # The device lane is int8 with the EXACT narrow-path values: every
        # downstream compile class is identical to PR 15.
        assert lane.dtype == np.int8
        assert np.array_equal(np.asarray(lane), narrow_codes(c))
        # Memoized: restaging the same column adds no packed bytes.
        lane2 = stage_codes(c, "test_packed_site")
        assert lane2 is lane
        assert metrics.counter("device.encoded.bytes_packed").value == packed1

    def test_flag_off_stages_narrow_not_packed(self, monkeypatch):
        monkeypatch.setenv(ENV_ENC, "1")
        monkeypatch.setenv(ENV, "0")
        _clear_caches()
        from hyperspace_tpu.engine.encoded_device import stage_codes

        c = self._lowcard_column(seed=6)
        packed0 = metrics.counter("device.encoded.bytes_packed").value
        lane = stage_codes(c, "test_packed_site_off")
        assert lane.dtype == np.int8  # narrow class still applies
        assert metrics.counter("device.encoded.bytes_packed").value == packed0

    def test_nulls_fold_into_reserved_lane(self, monkeypatch):
        monkeypatch.setenv(ENV_ENC, "1")
        monkeypatch.setenv(ENV, "1")
        _clear_caches()
        from hyperspace_tpu.engine.encoded_device import narrow_codes, stage_codes

        c = self._lowcard_column(with_nulls=True, seed=8)
        lane = stage_codes(c, "test_packed_nulls")
        assert np.array_equal(np.asarray(lane), narrow_codes(c))


# ---------------------------------------------------------------------------
# Flag oracle: byte-identical index files + results, both mesh ambients
# ---------------------------------------------------------------------------


class TestFlagOracle:
    @pytest.mark.parametrize("distributed", ["1", "0"])
    def test_build_byte_identical_across_flag(
        self, tmp_path, monkeypatch, distributed
    ):
        monkeypatch.setenv("HYPERSPACE_DISTRIBUTED", distributed)
        monkeypatch.setenv(ENV_ENC, "1")
        s = _session(tmp_path)
        base = str(tmp_path)
        _write_lowcard_pair(s, base, 2000, card=12, seed=5)
        hs = Hyperspace(s)
        f = s.read.parquet(os.path.join(base, "fact"))

        monkeypatch.setenv(ENV, "1")
        _clear_caches()
        hs.create_index(f, IndexConfig("packedIdx", ["sk"], ["val"]))
        monkeypatch.setenv(ENV, "0")
        _clear_caches()
        hs.create_index(f, IndexConfig("narrowIdx", ["sk"], ["val"]))
        monkeypatch.delenv(ENV, raising=False)

        hp = _dir_hashes(os.path.join(base, "indexes", "packedIdx", "v__=0"))
        hn = _dir_hashes(os.path.join(base, "indexes", "narrowIdx", "v__=0"))
        assert len(hp) > 0
        assert hp == hn

        # And the indexed join answers identically in this ambient.
        enable_hyperspace(s)
        d = s.read.parquet(os.path.join(base, "dim"))
        q = f.join(d, col("sk") == col("dk")).select("val", "w")
        monkeypatch.setenv(ENV, "1")
        _clear_caches()
        rows_on = q.sorted_rows()
        monkeypatch.setenv(ENV, "0")
        _clear_caches()
        rows_off = q.sorted_rows()
        assert rows_on == rows_off and len(rows_on) > 0

    def test_null_key_join_identical(self, tmp_path, monkeypatch):
        from hyperspace_tpu.engine import io as engine_io

        monkeypatch.setenv(ENV_ENC, "1")
        s = _session(tmp_path)
        base = str(tmp_path)
        lt = Table.from_pydict(
            {"k": ["a", "b", None, "c", "a", None], "lv": [1, 2, 3, 4, 5, 6]}
        )
        rt = Table.from_pydict({"k": ["b", "a", None, "d"], "rv": [10, 20, 30, 40]})
        engine_io.write_parquet(lt, os.path.join(base, "nl", "part-00000.parquet"))
        engine_io.write_parquet(rt, os.path.join(base, "nr", "part-00000.parquet"))

        def q():
            l = s.read.parquet(os.path.join(base, "nl"))
            r = s.read.parquet(os.path.join(base, "nr"))
            return sorted(
                l.join(r, col("k") == col("k")).select("k", "lv", "rv").collect().rows()
            )

        monkeypatch.setenv(ENV, "1")
        _clear_caches()
        rows_on = q()
        monkeypatch.setenv(ENV, "0")
        _clear_caches()
        rows_off = q()
        assert rows_on == rows_off
        assert rows_on == [("a", 1, 20), ("a", 5, 20), ("b", 2, 10)]


# ---------------------------------------------------------------------------
# Mesh wire: packed lanes shrink bytes_moved; compile classes stay bounded
# ---------------------------------------------------------------------------


class TestMeshPackedExchange:
    def test_exchange_bytes_moved_shrinks(self, tmp_path, monkeypatch):
        """Sub-byte wire lanes (4-bit biased codes + 1-bit validity + 16-bit
        row ids) vs the int8 coded exchange (int8 codes/validity + int32 row
        ids) for the SAME build: ≥1.8× fewer bytes on the wire."""
        monkeypatch.setenv("HYPERSPACE_DISTRIBUTED", "1")
        monkeypatch.setenv(ENV_ENC, "1")
        s = _session(tmp_path)
        base = str(tmp_path)
        _write_lowcard_pair(s, base, 3000, card=12, seed=9)
        hs = Hyperspace(s)
        f = s.read.parquet(os.path.join(base, "fact"))

        def moved_during(build):
            before = metrics.counter("parallel.exchange.bytes_moved").value
            build()
            return metrics.counter("parallel.exchange.bytes_moved").value - before

        monkeypatch.setenv(ENV, "1")
        _clear_caches()
        moved_on = moved_during(
            lambda: hs.create_index(f, IndexConfig("pkOn", ["sk"], ["val"]))
        )
        monkeypatch.setenv(ENV, "0")
        _clear_caches()
        moved_off = moved_during(
            lambda: hs.create_index(f, IndexConfig("pkOff", ["sk"], ["val"]))
        )
        monkeypatch.delenv(ENV, raising=False)
        assert moved_on > 0 and moved_off > 0
        assert moved_off / moved_on >= 1.8, (moved_off, moved_on)

    def test_no_per_cardinality_compile_classes(self, tmp_path, monkeypatch):
        """Two cardinalities in the SAME 4-bit class share one compiled
        exchange AND one compiled unpack: packing mints no new per-cardinality
        shapes."""
        monkeypatch.setenv("HYPERSPACE_DISTRIBUTED", "1")
        monkeypatch.setenv(ENV_ENC, "1")
        monkeypatch.setenv(ENV, "1")
        s = _session(tmp_path)
        base = str(tmp_path)
        hs = Hyperspace(s)
        rng = np.random.RandomState(23)
        for suffix, card in (("a", 6), ("b", 12)):
            s.write_parquet(
                {
                    "sk": np.array(
                        [f"k{v:02d}" for v in rng.randint(0, card, 2048)]
                    ),
                    "val": np.arange(2048, dtype=np.int64),
                },
                os.path.join(base, f"fact{suffix}"),
            )

        def compiles(lbl):
            return compile_log.program_summary().get(lbl, {}).get("compiles", 0)

        _clear_caches()
        fa = s.read.parquet(os.path.join(base, "facta"))
        hs.create_index(fa, IndexConfig("pcA", ["sk"], ["val"]))
        exchange_first = compiles("parallel.exchange")
        unpack_first = compiles("packed.unpack")
        assert exchange_first >= 1
        assert unpack_first >= 1
        fb = s.read.parquet(os.path.join(base, "factb"))
        hs.create_index(fb, IndexConfig("pcB", ["sk"], ["val"]))
        assert compiles("parallel.exchange") == exchange_first, (
            "a second cardinality in the same packed class recompiled the "
            "exchange"
        )
        assert compiles("packed.unpack") == unpack_first, (
            "a second cardinality in the same packed class recompiled the "
            "unpack program"
        )


# ---------------------------------------------------------------------------
# Compute on packed words: kernels vs their XLA/numpy oracles (interpret mode)
# ---------------------------------------------------------------------------


class TestComputeOnPacked:
    def test_packed_sort_matches_stable_argsort(self, monkeypatch):
        monkeypatch.setenv("HYPERSPACE_PALLAS_SORT", "1")
        import jax.numpy as jnp

        from hyperspace_tpu.ops.pallas_sort import sort_codes_packed

        rng = np.random.RandomState(2)
        for bits in pc.PACKED_BITS:
            B, cap = 8, 512
            top = (1 << bits) - 1
            mat = rng.randint(0, top, (B, cap))
            lens = rng.randint(0, cap + 1, B)
            for b in range(B):
                mat[b, lens[b] :] = top  # pads at the reserved top lane value
            words = pc.pack_rows_traced(jnp.asarray(mat), bits)
            codes_s, order = sort_codes_packed(words, bits)
            oracle_order = np.argsort(mat, axis=1, kind="stable")
            # Composite uniqueness => the unstable bitonic reproduces the
            # STABLE argsort exactly, ties included.
            assert np.array_equal(np.asarray(order), oracle_order), bits
            assert np.array_equal(
                np.asarray(codes_s),
                np.take_along_axis(mat, oracle_order, axis=1),
            ), bits

    def test_packed_probe_matches_searchsorted(self, monkeypatch):
        monkeypatch.setenv("HYPERSPACE_PALLAS_PROBE", "1")
        import jax.numpy as jnp

        from hyperspace_tpu.ops.pallas_probe import probe_packed_pallas

        rng = np.random.RandomState(4)
        bits = 4
        B, cap_l, cap_r = 8, 256, 512
        top = (1 << bits) - 1
        L = rng.randint(0, top, (B, cap_l))
        R = rng.randint(0, top, (B, cap_r))
        l_len = rng.randint(0, cap_l + 1, B)
        r_len = rng.randint(0, cap_r + 1, B)
        for b in range(B):
            L[b, l_len[b] :] = top
            R[b, r_len[b] :] = top
        L.sort(axis=1)
        R.sort(axis=1)
        lw = pc.pack_rows_traced(jnp.asarray(L), bits)
        rw = pc.pack_rows_traced(jnp.asarray(R), bits)
        lo, cnt = probe_packed_pallas(
            lw, rw, bits, jnp.asarray(l_len), jnp.asarray(r_len)
        )
        lo, cnt = np.asarray(lo), np.asarray(cnt)
        for b in range(B):
            n, m = l_len[b], r_len[b]
            exp_lo = np.minimum(np.searchsorted(R[b], L[b], "left"), m)
            exp_hi = np.minimum(np.searchsorted(R[b], L[b], "right"), m)
            exp_cnt = np.where(np.arange(cap_l) < n, exp_hi - exp_lo, 0)
            assert np.array_equal(cnt[b], exp_cnt), b
            assert np.array_equal(lo[b, :n], exp_lo[:n]), b

    def test_code_rep_probe_matches_widen_fallback(self, monkeypatch):
        """The full code-mode rep path: packed-kernel probe, widen-then-probe
        fallback, and a per-bucket numpy oracle all agree on (lo, counts)."""
        monkeypatch.setenv(ENV, "1")
        monkeypatch.setenv("HYPERSPACE_PALLAS_SORT", "1")
        from hyperspace_tpu.ops import bucket_join as bj

        rng = np.random.RandomState(6)
        card = 13  # probe class: card + 2 <= 16
        B = 16
        l_lens = rng.randint(0, 120, B)
        r_lens = rng.randint(0, 200, B)
        l_starts = np.concatenate([[0], np.cumsum(l_lens)])
        r_starts = np.concatenate([[0], np.cumsum(r_lens)])
        l_codes = rng.randint(0, card, l_starts[-1])
        r_codes = rng.randint(0, card, r_starts[-1])
        lrep = bj.pad_buckets_by_codes(l_codes, l_starts, card)
        rrep = bj.pad_buckets_by_codes(r_codes, r_starts, card)
        assert lrep is not None and rrep is not None
        assert lrep.bits == 4
        # Rep `order` maps sorted slots back to storage slots bijectively.
        for b in range(B):
            n = l_lens[b]
            got = np.sort(np.asarray(lrep.order)[b, :n])
            assert np.array_equal(got, np.arange(n)), b

        monkeypatch.setenv("HYPERSPACE_PALLAS_PROBE", "1")
        lo_k, cnt_k = bj.probe_code_ranges(lrep, rrep)
        monkeypatch.setenv("HYPERSPACE_PALLAS_PROBE", "0")
        lo_w, cnt_w = bj.probe_code_ranges(lrep, rrep)
        assert np.array_equal(np.asarray(cnt_k), np.asarray(cnt_w))
        for b in range(B):
            ls = np.sort(l_codes[l_starts[b] : l_starts[b + 1]])
            rs = np.sort(r_codes[r_starts[b] : r_starts[b + 1]])
            exp = np.searchsorted(rs, ls, "right") - np.searchsorted(rs, ls, "left")
            assert np.array_equal(np.asarray(cnt_k)[b, : len(ls)], exp), b

    def test_rep_requires_probe_class_and_no_nulls(self, monkeypatch):
        monkeypatch.setenv(ENV, "1")
        from hyperspace_tpu.ops import bucket_join as bj

        starts = np.array([0, 4])
        codes = np.array([0, 1, 2, 1])
        assert bj.pad_buckets_by_codes(codes, starts, 15) is None  # 15+2 > 16
        assert bj.pad_buckets_by_codes(codes, starts, 13, has_nulls=True) is None
        assert bj.pad_buckets_by_codes(codes, starts, 13) is not None

    def test_packed_build_sort_matches_sort_perm(self, monkeypatch):
        """The int32 (bucket|code|row) composite build sort reproduces the
        device variadic sort's canonical order exactly — nulls included."""
        monkeypatch.setenv(ENV, "1")
        monkeypatch.setenv("HYPERSPACE_PALLAS_SORT", "1")
        import jax.numpy as jnp

        from hyperspace_tpu.engine.table import STRING
        from hyperspace_tpu.ops.hashing import bucket_id
        from hyperspace_tpu.ops.partition import _sort_perm, pallas_packed_build_sort

        rng = np.random.RandomState(8)
        card, n, nb = 12, 3000, NUM_BUCKETS
        dictionary = np.sort(np.array([f"k{i:02d}" for i in range(card)]))
        codes = rng.randint(0, card, n).astype(np.int32)
        codes[::11] = -1  # null lane rides the reserved biased 0
        valid = codes >= 0
        c = Column(STRING, np.where(valid, codes, 0), dictionary, valid)
        lane = jnp.asarray(codes.astype(np.int8))
        b = bucket_id([c], [lane], nb)
        res = pallas_packed_build_sort(b, lane, card, n, nb)
        assert res is not None
        perm, sorted_b = res
        operm, osb = _sort_perm(b, (jnp.asarray(codes),), n)
        assert np.array_equal(perm, np.asarray(operm))
        assert np.array_equal(sorted_b, np.asarray(osb))

    def test_packed_build_sort_respects_flag_and_budget(self, monkeypatch):
        import jax.numpy as jnp

        from hyperspace_tpu.ops.partition import pallas_packed_build_sort

        monkeypatch.setenv("HYPERSPACE_PALLAS_SORT", "1")
        b = jnp.zeros(400, jnp.int32)
        lane = jnp.zeros(400, jnp.int8)
        monkeypatch.setenv(ENV, "0")
        assert pallas_packed_build_sort(b, lane, 12, 400, NUM_BUCKETS) is None
        monkeypatch.setenv(ENV, "1")
        # Cardinality past every packed class: no composite encoding exists.
        assert pallas_packed_build_sort(b, lane, 200, 400, NUM_BUCKETS) is None
