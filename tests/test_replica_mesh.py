"""Replica fleet across a REAL `jax.distributed` 2-process CPU mesh.

The CI-able stand-in for a multi-host pod (docs/serving.md "Replica fleet"):
two local processes each initialize the JAX distributed runtime (so each is
a genuine jax "host" with its own process_id), join one on-lake replica
registry, and must agree on membership, rendezvous ownership, and epoch
invalidation — and both must return byte-identical query results over the
shared lake.

Marked ``slow``: coordinator startup costs seconds, and tier-1 (`-m 'not
slow'`) skips it; the dedicated CI mesh leg runs it explicitly.
"""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_CHILD = r"""
import json, os, sys, time
sys.path.insert(0, {repo!r})
os.environ["JAX_PLATFORMS"] = "cpu"
proc_id = int(sys.argv[1])
port = sys.argv[2]

import jax

jax.distributed.initialize(
    coordinator_address=f"localhost:{{port}}", num_processes=2, process_id=proc_id
)

from hyperspace_tpu.engine import HyperspaceSession, col
from hyperspace_tpu.serve import replicas as R

out = {{"proc": proc_id, "jax_procs": jax.process_count()}}
rid = R.join_fleet()
out["replica_id"] = rid

deadline = time.time() + 30
while len(R.live_replicas(refresh=True)) < 2:
    assert time.time() < deadline, "fleet barrier timeout"
    time.sleep(0.05)
members = R.live_replicas()
out["members"] = members
out["owners"] = {{f"key{{i}}": R.owner_of(f"key{{i}}", members) for i in range(20)}}

# Process 0 publishes an invalidation; process 1 must observe the flip.
cursor = {{}}
R.check_invalidation(cursor)
if proc_id == 0:
    R.publish_invalidation("meshIdx", 42)
    out["observed"] = True
else:
    deadline = time.time() + 15
    seen = False
    while not seen and time.time() < deadline:
        seen = R.check_invalidation(cursor)
        time.sleep(0.02)
    out["observed"] = seen
    out["epoch_entry"] = R.read_epoch().get("entries", {{}}).get("meshIdx")

# Both processes answer the same query over the shared lake.
s = HyperspaceSession(warehouse=os.environ["MESH_WAREHOUSE"])
rows = (
    s.read.parquet(os.path.join(os.environ["MESH_WAREHOUSE"], "t"))
    .filter(col("k") < 50)
    .select("k", "v")
    .collect()
    .sorted_rows()
)
out["rows"] = [[int(a), int(b)] for a, b in rows]
R.leave_fleet()
print("RESULT " + json.dumps(out), flush=True)
"""


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


@pytest.mark.slow
def test_two_process_jax_distributed_fleet(tmp_path):
    wh = str(tmp_path / "wh")
    from hyperspace_tpu.engine import HyperspaceSession

    sess = HyperspaceSession(warehouse=wh)
    sess.write_parquet(
        {
            "k": np.arange(500, dtype=np.int64),
            "v": (np.arange(500, dtype=np.int64) * 3) % 101,
        },
        os.path.join(wh, "t"),
    )
    reg = str(tmp_path / "registry")
    port = _free_port()
    env = dict(os.environ)
    env.update(
        {
            "JAX_PLATFORMS": "cpu",
            "HYPERSPACE_REPLICAS": "1",
            "HYPERSPACE_REPLICA_DIR": reg,
            "HYPERSPACE_REPLICA_VIEW_S": "0",
            "HYPERSPACE_REPLICA_EPOCH_CHECK_S": "0",
            "MESH_WAREHOUSE": wh,
        }
    )
    env.pop("XLA_FLAGS", None)
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _CHILD.format(repo=REPO), str(i), str(port)],
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
        )
        for i in range(2)
    ]
    results = {}
    for p in procs:
        try:
            out, err = p.communicate(timeout=180)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        assert p.returncode == 0, err[-800:]
        line = [l for l in out.splitlines() if l.startswith("RESULT ")][-1]
        r = json.loads(line[len("RESULT ") :])
        results[r["proc"]] = r

    a, b = results[0], results[1]
    # Both are genuine jax.distributed processes...
    assert a["jax_procs"] == 2 and b["jax_procs"] == 2
    # ...agreeing on fleet membership and rendezvous ownership...
    assert a["replica_id"] != b["replica_id"]
    assert a["members"] == b["members"]
    assert set(a["members"]) == {a["replica_id"], b["replica_id"]}
    assert a["owners"] == b["owners"]
    owned = set(a["owners"].values())
    assert owned == set(a["members"]), "both replicas should own some keys"
    # ...the epoch publish from proc 0 reached proc 1 (no TTL wait)...
    assert b["observed"] is True
    assert b["epoch_entry"] == 42
    # ...and both answered the shared-lake query byte-identically.
    assert a["rows"] == b["rows"] and a["rows"]
