"""Stage-level cost attribution (ISSUE 19 tentpole).

The contract under test: `stage_scope` labels the ambient stage on a
contextvar that pool workers inherit, every cost-vector counter bills the
ambient stage (or the visible ``<unlabeled>`` bucket, so per-stage totals
reconcile with the whole-query ledger BY CONSTRUCTION), the mesh exchange
and H2D upload own dedicated lanes, and the closed ledger's ``stages`` key
feeds the planner's stage-grain learning — a mispriced knob flips on its
stage-local subtotal even when an unrelated stage dominates the wall,
which whole-wall learning cannot do. ``HYPERSPACE_STAGE_ATTRIBUTION=0`` is
zero-cost-off: no stage ledger is ever touched and results are
byte-identical. v1 (pre-stage) planner outcome records keep folding
wall-only; the Chrome-trace conversion gives each stage its own lane.
"""

import glob
import importlib.util
import json
import os
import time

import numpy as np
import pytest

from hyperspace_tpu import resilience
from hyperspace_tpu.engine import HyperspaceSession, col
from hyperspace_tpu.engine import io as engine_io
from hyperspace_tpu.engine.table import Table
from hyperspace_tpu.plananalysis import attribution, costmodel, planner
from hyperspace_tpu.telemetry import accounting, history, stage_ledger, tracing

CLEAN_ENVS = (
    planner.ENV_PLANNER,
    planner.ENV_PLANNER_DIR,
    planner.ENV_MIN_SAMPLES,
    planner.ENV_DRIFT_X,
    stage_ledger.ENV_STAGE_ATTRIBUTION,
    stage_ledger.ENV_TIMELINE_DIR,
    engine_io.ENV_DECODE_THREADS,
    "HYPERSPACE_HISTORY",
    "HYPERSPACE_HISTORY_DIR",
    "HYPERSPACE_ACCOUNTING",
)


@pytest.fixture(autouse=True)
def _clean(monkeypatch):
    for k in CLEAN_ENVS + tuple(costmodel.KNOB_ENV.values()):
        monkeypatch.delenv(k, raising=False)
    planner.reset()
    history.reset_stores()
    yield
    planner.reset()
    history.reset_stores()


@pytest.fixture()
def session(tmp_path):
    return HyperspaceSession(warehouse=str(tmp_path))


def _write_parts(path: str, parts: int = 4, rows: int = 400) -> None:
    """Key-sorted parts with 4 row groups each: an `isin` point filter
    prunes 3 of 4 groups per file, so pruned decodes (-> bytes_decoded)
    actually happen, one decode job per file (-> the pool engages)."""
    for j in range(parts):
        engine_io.write_parquet(
            Table.from_pydict(
                {
                    "k": (np.arange(rows, dtype=np.int64) + j * rows),
                    "v": np.arange(rows, dtype=np.float64),
                }
            ),
            os.path.join(path, f"part-{j:05d}.parquet"),
            row_group_rows=max(rows // 4, 1),
        )


def _scan_agg(session, path):
    return session.read.parquet(path).group_by("k").agg(total=("v", "sum"))


def _pruned_scan(session, path, parts: int = 4, rows: int = 400):
    return session.read.parquet(path).filter(
        col("k").isin([j * rows + 7 for j in range(parts)])
    )


# ---------------------------------------------------------------------------
# stage_scope: walls bank on the ambient query scope
# ---------------------------------------------------------------------------


def test_stage_scope_banks_walls_on_query_scope():
    with resilience.query_scope("q-walls"):
        assert stage_ledger.query_stage_walls() is None  # nothing labeled yet
        with stage_ledger.stage_scope("probe"):
            assert stage_ledger.current_stage() == "probe"
            time.sleep(0.01)
        with stage_ledger.stage_scope("pad"):
            pass
        walls = stage_ledger.query_stage_walls()
        assert walls is not None and set(walls) == {"probe", "pad"}
        assert walls["probe"] >= 0.01
    assert stage_ledger.current_stage() is None


def test_stage_scope_nested_innermost_wins():
    with resilience.query_scope("q-nest"):
        with stage_ledger.stage_scope("outer"):
            with stage_ledger.stage_scope("inner"):
                assert stage_ledger.current_stage() == "inner"
            assert stage_ledger.current_stage() == "outer"
        walls = stage_ledger.query_stage_walls()
        assert set(walls) == {"outer", "inner"}


def test_no_scope_means_no_walls():
    with stage_ledger.stage_scope("orphan"):
        pass  # no ambient query scope: nowhere to bank, must not raise
    assert stage_ledger.query_stage_walls() is None


# ---------------------------------------------------------------------------
# Pool workers inherit the submitting stage; counters reconcile
# ---------------------------------------------------------------------------


def test_threaded_decode_workers_bill_decode_stage(session, tmp_path, monkeypatch):
    monkeypatch.setenv("HYPERSPACE_ACCOUNTING", "1")
    monkeypatch.setenv(engine_io.ENV_DECODE_THREADS, "2")
    path = os.path.join(str(tmp_path), "t")
    _write_parts(path)
    out = _pruned_scan(session, path).collect()
    assert out.num_rows == 4
    d = accounting.recent_ledgers()[-1].to_dict()
    stages = d.get("stages")
    assert isinstance(stages, dict) and stages
    # The decode pool's workers billed the decode lane, not <unlabeled>.
    assert "decode" in stages
    assert stages["decode"]["bytes_decoded"] > 0
    assert stages["decode"]["wall_s"] > 0


def test_stage_totals_reconcile_with_ledger_counters(session, tmp_path, monkeypatch):
    monkeypatch.setenv("HYPERSPACE_ACCOUNTING", "1")
    path = os.path.join(str(tmp_path), "t")
    _write_parts(path)
    _pruned_scan(session, path).collect()
    d = accounting.recent_ledgers()[-1].to_dict()
    stages = d.get("stages")
    assert isinstance(stages, dict)
    # Every stage-attributed counter sums (across stages INCLUDING the
    # <unlabeled> bucket) to the whole-query ledger counter exactly — the
    # by-construction reconciliation the <unlabeled> bucket exists for.
    for counter_key, field in stage_ledger._COUNTER_VECTOR.items():
        total = d.get(counter_key) or 0
        if not total:
            continue
        staged = sum(vec.get(field, 0) for vec in stages.values())
        assert staged == pytest.approx(total, rel=1e-6), (counter_key, d)
    assert d["bytes_decoded"] > 0  # the loop above exercised at least bytes


# ---------------------------------------------------------------------------
# Zero-cost-off: counting oracle + byte-identical results
# ---------------------------------------------------------------------------


def test_attribution_off_is_zero_cost_and_byte_identical(
    session, tmp_path, monkeypatch
):
    monkeypatch.setenv("HYPERSPACE_ACCOUNTING", "1")
    path = os.path.join(str(tmp_path), "t")
    _write_parts(path)

    calls = []
    real = stage_ledger._scope_ledger

    def counting(create):
        calls.append(create)
        return real(create)

    monkeypatch.setattr(stage_ledger, "_scope_ledger", counting)

    monkeypatch.setenv(stage_ledger.ENV_STAGE_ATTRIBUTION, "0")
    rows_off = _scan_agg(session, path).collect().rows()
    assert calls == []  # the counting oracle: off never touches the ledger
    d_off = accounting.recent_ledgers()[-1].to_dict()
    assert "stages" not in d_off

    monkeypatch.setenv(stage_ledger.ENV_STAGE_ATTRIBUTION, "1")
    rows_on = _scan_agg(session, path).collect().rows()
    assert calls  # on: the same query labels stages
    assert rows_on == rows_off  # byte-identical results in both states


# ---------------------------------------------------------------------------
# Dedicated lanes: mesh exchange
# ---------------------------------------------------------------------------


def test_mesh_exchange_bills_exchange_stage():
    import jax.numpy as jnp

    from hyperspace_tpu.engine.table import Column
    from hyperspace_tpu.ops.hashing import _SEED1, column_hash_u32
    from hyperspace_tpu.parallel import distributed_bucketize, make_mesh

    mesh = make_mesh(8)
    rng = np.random.RandomState(5)
    keys = rng.randint(0, 1000, size=512).astype(np.int64)
    kcol = Column.from_values(keys)
    h1 = column_hash_u32(kcol, jnp.asarray(keys), _SEED1)
    with resilience.query_scope("q-mesh"):
        distributed_bucketize(mesh, h1, [jnp.asarray(keys)], [jnp.asarray(keys)], 32)
        walls = stage_ledger.query_stage_walls()
    assert walls is not None and walls.get("exchange", 0) > 0


# ---------------------------------------------------------------------------
# Planner: v1 records fold, stage-grain flips beat whole-wall
# ---------------------------------------------------------------------------


def test_v1_outcome_records_fold_wall_only(tmp_path, monkeypatch):
    store_dir = os.path.join(str(tmp_path), "store")
    os.makedirs(store_dir)
    v1 = {
        "kind": "planner_outcome",
        "ts": 1.0,
        "fingerprint": "fp-v1",
        "outcomes": {"streaming": {"arm": "on", "wall_s": 0.05, "predicted_s": 0.01}},
    }
    v2 = {
        "schema_version": 2,
        "kind": "planner_outcome",
        "ts": 2.0,
        "fingerprint": "fp-v1",
        "outcomes": {
            "streaming": {
                "arm": "on",
                "wall_s": 0.07,
                "predicted_s": 0.01,
                "stage_s": 0.002,
            }
        },
    }
    with open(os.path.join(store_dir, "planner-old.jsonl"), "w") as fh:
        fh.write(json.dumps(v1) + "\n")
        fh.write(json.dumps(v2) + "\n")
    monkeypatch.setenv(planner.ENV_PLANNER_DIR, store_dir)
    planner.reset()
    st = planner._outcome_store().stat("fp-v1", "streaming", "on")
    # Both versions fold walls; only the v2 record carries stage stats.
    assert st.n == 2 and st.mean_wall() == pytest.approx(0.06)
    assert st.stage_n == 1 and st.mean_stage() == pytest.approx(0.002)


def test_v2_records_round_trip_through_observe(tmp_path, monkeypatch):
    store_dir = os.path.join(str(tmp_path), "store")
    monkeypatch.setenv(planner.ENV_PLANNER_DIR, store_dir)
    store = planner._outcome_store()
    store.observe(
        "fp-rt",
        {"streaming": {"arm": "off", "wall_s": 0.5, "predicted_s": 0.1, "stage_s": 0.02}},
    )
    recs = []
    for f in glob.glob(os.path.join(store_dir, "planner-*.jsonl")):
        recs += [json.loads(line) for line in open(f)]
    assert recs and recs[0]["schema_version"] == 2
    planner.reset()  # restart: the persisted stage_s must fold back
    st = planner._outcome_store().stat("fp-rt", "streaming", "off")
    assert st.stage_n == 1 and st.mean_stage() == pytest.approx(0.02)


def _mispriced_streaming(stats, cal):
    est = {k: (True, False, 0.0, 0.0) for k in costmodel.KNOBS}
    est["streaming"] = (False, True, 0.01, 0.011)  # model prefers OFF
    est["chunk_rows"] = (4_000_000, 4_000_000, 0.0, 0.0)
    est["hash_quantize"] = (False, True, 0.0, 0.0)
    return est


def test_stage_grain_flip_beats_whole_wall(session, tmp_path, monkeypatch):
    """Equal walls (an unrelated stage dominates both arms), decisive stage
    subtotals: stage-grain learning flips to the measured-better arm while
    the identical wall-only history stays on the model arm."""
    monkeypatch.setenv(planner.ENV_MIN_SAMPLES, "2")
    monkeypatch.setattr(costmodel, "estimate", _mispriced_streaming)
    src = os.path.join(str(tmp_path), "t")
    _write_parts(src, parts=1, rows=50)
    phys = _scan_agg(session, src).physical_plan()

    # Wall-only history: both arms identical at 1.0s -> no flip margin.
    monkeypatch.setenv(planner.ENV_PLANNER_DIR, os.path.join(str(tmp_path), "w"))
    planner.reset()
    store = planner._outcome_store()
    for _ in range(2):
        store.observe("fp-g", {"streaming": {"arm": "off", "wall_s": 1.0, "predicted_s": 0.01}})
        store.observe("fp-g", {"streaming": {"arm": "on", "wall_s": 1.0, "predicted_s": 0.011}})
    pd = planner.decide(phys, "fp-g")
    assert pd.decisions["streaming"].source == "model"
    assert pd.decisions["streaming"].value is False

    # Same walls PLUS stage subtotals: on's streaming-governed stages are
    # 4x cheaper -> measured flip despite indistinguishable walls.
    monkeypatch.setenv(planner.ENV_PLANNER_DIR, os.path.join(str(tmp_path), "s"))
    planner.reset()
    store = planner._outcome_store()
    for _ in range(2):
        store.observe(
            "fp-g",
            {"streaming": {"arm": "off", "wall_s": 1.0, "predicted_s": 0.01, "stage_s": 0.08}},
        )
        store.observe(
            "fp-g",
            {"streaming": {"arm": "on", "wall_s": 1.0, "predicted_s": 0.011, "stage_s": 0.02}},
        )
    pd = planner.decide(phys, "fp-g")
    assert pd.decisions["streaming"].source == "measured"
    assert pd.decisions["streaming"].value is True


def test_observe_records_knob_stage_subtotals(session, tmp_path, monkeypatch):
    monkeypatch.setenv(planner.ENV_PLANNER_DIR, os.path.join(str(tmp_path), "s"))
    src = os.path.join(str(tmp_path), "t")
    _write_parts(src, parts=1, rows=50)
    phys = _scan_agg(session, src).physical_plan()
    pd = planner.decide(phys, "fp-obs")
    planner.observe(
        pd, 0.5, stages={"decode": 0.1, "filter": 0.02, "pad": 0.01, "h2d": 0.03}
    )
    st = planner._outcome_store().stat(
        "fp-obs", "streaming", planner.arm_label(pd.decisions["streaming"].value)
    )
    # streaming governs decode/filter/partial/merge -> 0.12 of the snapshot.
    assert st.stage_n == 1 and st.mean_stage() == pytest.approx(0.12)
    # pushdown governs decode only.
    stp = planner._outcome_store().stat(
        "fp-obs", "pushdown", planner.arm_label(pd.decisions["pushdown"].value)
    )
    assert stp.mean_stage() == pytest.approx(0.1)


# ---------------------------------------------------------------------------
# attribution units
# ---------------------------------------------------------------------------


def test_knob_stage_seconds_subtotals_and_fallback():
    walls = {"pad": 0.01, "probe": 0.02, "verify": 0.03, "decode": 9.0}
    assert attribution.knob_stage_seconds("join_size_classes", walls) == pytest.approx(
        0.06
    )
    assert attribution.knob_stage_seconds("pushdown", walls) == pytest.approx(9.0)
    # None -> whole-wall fallback: no snapshot, unknown knob, or no overlap.
    assert attribution.knob_stage_seconds("join_size_classes", None) is None
    assert attribution.knob_stage_seconds("no_such_knob", walls) is None
    assert attribution.knob_stage_seconds("packed_codes", {"decode": 1.0}) is None


def test_knob_stages_cover_every_costmodel_knob():
    assert set(attribution.KNOB_STAGES) == set(costmodel.KNOBS)


# ---------------------------------------------------------------------------
# Chrome-trace conversion: one lane per stage
# ---------------------------------------------------------------------------


def _span(name, span_id, parent, start, dur):
    return {
        "query_id": "q-trace",
        "span_id": span_id,
        "parent_id": parent,
        "name": name,
        "start_s": start,
        "duration_s": dur,
        "status": "ok",
        "attrs": {},
    }


def test_chrome_trace_gives_each_stage_its_own_lane():
    spans = [
        _span("query:collect", "r", None, 0.0, 1.0),
        _span("join:stages", "s", "r", 0.1, 0.8),
        _span("join:pad", "p1", "s", 0.1, 0.2),
        _span("join:probe", "p2", "s", 0.3, 0.3),
        _span("join:verify", "p3", "s", 0.6, 0.2),
        _span("op:scan", "o1", "r", 0.0, 0.1),
        _span("worker:decode", "w1", "r", 0.0, 0.05),
    ]
    doc = stage_ledger.chrome_trace(spans)
    lanes = doc["otherData"]["lanes"]
    stage_lanes = [ln for ln in lanes if ln.startswith("stage:")]
    assert sorted(stage_lanes) == ["stage:pad", "stage:probe", "stage:verify"]
    assert "query" in lanes and "ops" in lanes and "workers" in lanes
    metas = [e for e in doc["traceEvents"] if e["ph"] == "M"]
    xs = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    assert {m["args"]["name"] for m in metas} == set(lanes)
    assert len(xs) == len(spans)
    by_name = {e["name"]: e for e in xs}
    # Events on the same lane share a tid; different stages never do.
    tids = {by_name[n]["tid"] for n in ("join:pad", "join:probe", "join:verify")}
    assert len(tids) == 3
    assert by_name["join:probe"]["ts"] == pytest.approx(0.3e6)
    assert by_name["join:probe"]["dur"] == pytest.approx(0.3e6)
    assert json.dumps(doc)


def test_live_timeline_capture_writes_per_query_file(session, tmp_path, monkeypatch):
    tdir = os.path.join(str(tmp_path), "timelines")
    monkeypatch.setenv(stage_ledger.ENV_TIMELINE_DIR, tdir)
    path = os.path.join(str(tmp_path), "t")
    _write_parts(path, parts=2)
    with tracing.capture() as cap:
        _scan_agg(session, path).collect()
    f = os.path.join(tdir, f"timeline-{cap.trace.query_id}.json")
    assert os.path.exists(f)
    doc = json.load(open(f))
    assert doc["otherData"]["query_id"] == cap.trace.query_id
    assert any(e["ph"] == "X" for e in doc["traceEvents"])


# ---------------------------------------------------------------------------
# Surfacing: explain, exporter, hsreport
# ---------------------------------------------------------------------------


def test_explain_analyze_renders_attribution_section(session, tmp_path, monkeypatch):
    monkeypatch.setenv("HYPERSPACE_ACCOUNTING", "1")
    path = os.path.join(str(tmp_path), "t")
    _write_parts(path)
    txt = _scan_agg(session, path).explain(analyze=True)
    assert "Attribution (per-stage cost vectors):" in txt
    assert "<unlabeled>" in txt or "decode" in txt
    assert "[knobs:" in txt
    # Attribution off: the section disappears, the rest of explain survives.
    monkeypatch.setenv(stage_ledger.ENV_STAGE_ATTRIBUTION, "0")
    txt_off = _scan_agg(session, path).explain(analyze=True)
    assert "Attribution (per-stage cost vectors):" not in txt_off
    assert "Resource ledger (this query):" in txt_off


def test_exporter_frame_carries_planner_activity(session, tmp_path, monkeypatch):
    from hyperspace_tpu.telemetry.exporter import MetricsExporter

    monkeypatch.setenv(planner.ENV_PLANNER_DIR, os.path.join(str(tmp_path), "s"))
    src = os.path.join(str(tmp_path), "t")
    _write_parts(src, parts=1, rows=50)
    _scan_agg(session, src).collect()  # at least one planner decision
    ex = MetricsExporter(os.path.join(str(tmp_path), "m.jsonl"), interval_s=60)
    frame = ex._frame()
    assert "planner" in frame
    assert frame["planner"].get("streaming", {}).get("decisions", 0) >= 1


def _load_hsreport():
    path = os.path.join(os.path.dirname(__file__), "..", "tools", "hsreport.py")
    if not os.path.exists(path):
        pytest.skip("tools/hsreport.py not present (installed-wheel run)")
    spec = importlib.util.spec_from_file_location("hsreport", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_hsreport_stage_drift_and_fingerprint_filter(session, tmp_path, monkeypatch):
    hdir = os.path.join(str(tmp_path), "hist")
    monkeypatch.setenv("HYPERSPACE_HISTORY", "1")
    monkeypatch.setenv("HYPERSPACE_HISTORY_DIR", hdir)
    monkeypatch.setenv("HYPERSPACE_ACCOUNTING", "1")
    path = os.path.join(str(tmp_path), "t")
    _write_parts(path)
    for _ in range(3):
        _scan_agg(session, path).collect()
    mod = _load_hsreport()
    report = mod.build_report(hdir, top=10, recent_k=2)
    rows = report.get("stage_drift")
    assert rows, "stage drift table empty despite staged ledgers"
    row = rows[0]
    assert {
        "fingerprint",
        "stage",
        "baseline_n",
        "expected_wall_s",
        "recent_n",
        "actual_wall_s",
        "ratio",
    } <= set(row)
    assert "stage drift" in mod.render(report)

    fp = row["fingerprint"]
    filt = mod.build_report(hdir, top=10, recent_k=2, fingerprint=fp[:8])
    assert filt["fingerprint_filter"] == fp[:8]
    assert all(r["fingerprint"].startswith(fp[:8]) for r in filt["stage_drift"])
    miss = mod.build_report(hdir, top=10, recent_k=2, fingerprint="zzzz-no-such")
    assert not miss.get("stage_drift") and not miss.get("classes")


# ---------------------------------------------------------------------------
# Satellite: multiway star joins render per-dimension stage walls
# ---------------------------------------------------------------------------


def test_star_explain_renders_per_dimension_walls(tmp_path, monkeypatch):
    from hyperspace_tpu import IndexConfig, IndexConstants
    from hyperspace_tpu.engine import physical as phys
    from hyperspace_tpu.hyperspace import Hyperspace, enable_hyperspace

    monkeypatch.setenv("HYPERSPACE_QUERY_STREAMING", "1")
    phys.clear_device_memos()
    s = HyperspaceSession(warehouse=str(tmp_path))
    s.conf.set(IndexConstants.INDEX_SYSTEM_PATH, str(tmp_path / "indexes"))
    s.conf.set(IndexConstants.INDEX_NUM_BUCKETS, 8)
    hs = Hyperspace(s)
    rng = np.random.RandomState(3)
    n = 2000
    s.write_parquet(
        {
            "k1": rng.randint(0, 40, n).astype(np.int64),
            "k2": rng.randint(0, 20, n).astype(np.int64),
            "v": rng.randint(0, 100, n).astype(np.int64),
        },
        str(tmp_path / "fact"),
    )
    for name, card, grp in (("dim1", 40, "g1"), ("dim2", 20, "g2")):
        s.write_parquet(
            {
                f"d{name[-1]}": np.arange(card, dtype=np.int64),
                grp: rng.randint(0, 5, card).astype(np.int64),
            },
            str(tmp_path / name),
        )
        hs.create_index(
            s.read.parquet(str(tmp_path / name)),
            IndexConfig(f"star_{name}", [f"d{name[-1]}"], [grp]),
        )
    enable_hyperspace(s)
    q = (
        s.read.parquet(str(tmp_path / "fact"))
        .join(s.read.parquet(str(tmp_path / "dim1")), col("k1") == col("d1"))
        .join(s.read.parquet(str(tmp_path / "dim2")), col("k2") == col("d2"))
        .group_by("g1")
        .agg(t=("v", "sum"))
    )
    pp = q.physical_plan()
    assert any(isinstance(nd, phys.MultiwayJoinExec) for nd in pp.collect_nodes())
    txt = q.explain(analyze=True)
    assert "join stages:" in txt
    assert "dim[star_dim1]:" in txt or "dim[0]:" in txt
    assert "probe=" in txt
