"""Workload history observatory: plan fingerprints, the on-lake history
store, SLO monitoring, sink rotation, and the reporting tools.

Pins the PR-11 contracts:
- plan fingerprints are CLASS identities: stable across identical queries
  and literal values, different across predicate structure, ambient flag
  posture, and index generations (log_entry_id);
- the store is OCC-consistent under concurrent writers (per-process
  segments), survives restart (baselines re-fold from disk), bounds
  segments (rotation), compacts dead writers' segments into checkpoint
  records (claim-by-rename), and tolerates torn lines after SIGKILL;
- anomalies are flagged at ledger close (Nσ over the class baseline) into
  the counter, the root span, and the exporter stream — warn-once;
- ``HYPERSPACE_HISTORY`` unset = zero cost: no fingerprinting, no files;
- the trace/metrics JSONL sinks rotate at their size caps and the final
  exporter frame still lands;
- the serving SLO monitor computes compliance and burn rates per lane,
  and `tools/hsreport.py` / `tools/bench_compare.py --history` read the
  same store the engine writes.
"""

import glob
import importlib.util
import json
import os
import signal
import subprocess
import sys
import threading
import time

import pytest

from hyperspace_tpu.engine import HyperspaceSession, col
from hyperspace_tpu.plananalysis import fingerprint as fngr
from hyperspace_tpu.telemetry import (
    accounting,
    exporter,
    history,
    metrics,
    slo,
    tracing,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture()
def session(tmp_path):
    return HyperspaceSession(warehouse=str(tmp_path))


@pytest.fixture(autouse=True)
def _fresh_history_state():
    yield
    history.reset_stores()
    slo.reset()


def _write_table(session, path, n=200):
    session.write_parquet(
        {
            "k": list(range(n)),
            "grp": [i % 5 for i in range(n)],
            "v": [float(i) for i in range(n)],
        },
        path,
    )


def _ledger(name="query:collect", wall=0.02, qid=None, lane=None, **fields):
    d = {
        "query_id": qid or os.urandom(4).hex(),
        "name": name,
        "wall_s": wall,
        "rows_produced": 10,
    }
    if lane is not None:
        d["lane"] = lane
    d.update(fields)
    return d


def _tool(name):
    path = os.path.join(REPO, "tools", f"{name}.py")
    if not os.path.exists(path):
        pytest.skip(f"tools/{name}.py not present (installed-wheel run)")
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ---------------------------------------------------------------------------
# Plan fingerprints
# ---------------------------------------------------------------------------


class TestFingerprint:
    def test_same_query_and_literal_rotation_share_a_class(self, session, tmp_path):
        t = os.path.join(str(tmp_path), "t")
        _write_table(session, t)
        df = session.read.parquet(t)
        fp1 = fngr.plan_fingerprint(df.filter(col("k") == 3).physical_plan())
        fp2 = fngr.plan_fingerprint(df.filter(col("k") == 3).physical_plan())
        fp3 = fngr.plan_fingerprint(df.filter(col("k") == 77).physical_plan())
        assert fp1 == fp2 == fp3  # literal VALUES are abstracted: one class
        # ... but a different literal TYPE or column is a different class.
        fp4 = fngr.plan_fingerprint(df.filter(col("v") == 3.0).physical_plan())
        assert fp4 != fp1
        fp5 = fngr.plan_fingerprint(
            df.group_by("grp").agg(n=("v", "count")).physical_plan()
        )
        assert fp5 != fp1

    def test_flag_posture_changes_fingerprint(self, session, tmp_path, monkeypatch):
        t = os.path.join(str(tmp_path), "t")
        _write_table(session, t)
        q = session.read.parquet(t).filter(col("k") == 3)
        fp_default = fngr.plan_fingerprint(q.physical_plan())
        monkeypatch.setenv("HYPERSPACE_QUERY_STREAMING", "0")
        assert fngr.plan_fingerprint(q.physical_plan()) != fp_default

    def test_index_generation_changes_fingerprint(self, session, tmp_path):
        from hyperspace_tpu import IndexConfig, IndexConstants
        from hyperspace_tpu.hyperspace import Hyperspace, enable_hyperspace

        session.conf.set(
            IndexConstants.INDEX_SYSTEM_PATH, os.path.join(str(tmp_path), "idx")
        )
        session.conf.set(IndexConstants.INDEX_NUM_BUCKETS, 4)
        t = os.path.join(str(tmp_path), "t")
        _write_table(session, t)
        hs = Hyperspace(session)
        hs.create_index(
            session.read.parquet(t), IndexConfig("fpIdx", ["k"], ["v"])
        )
        enable_hyperspace(session)
        q = session.read.parquet(t).filter(col("k") == 3).select("v")
        fp1 = fngr.plan_fingerprint(q.physical_plan())
        assert "fpIdx" in q.explain_string()  # the rewrite actually applied
        hs.refresh_index("fpIdx")
        fp2 = fngr.plan_fingerprint(q.physical_plan())
        assert fp1 != fp2  # log_entry_id advanced: a new cost class

    def test_fingerprint_rides_ledger_and_root_span(
        self, session, tmp_path, monkeypatch
    ):
        monkeypatch.setenv(accounting.ENV_ACCOUNTING, "1")
        t = os.path.join(str(tmp_path), "t")
        _write_table(session, t)
        df = session.read.parquet(t).filter(col("k") == 3)
        with tracing.capture() as cap:
            df.collect()
        led = accounting.recent_ledgers()[-1].to_dict()
        expected = fngr.plan_fingerprint(df.physical_plan())
        assert led["plan_fingerprint"] == expected
        assert cap.trace.root.attrs["plan_fingerprint"] == expected


# ---------------------------------------------------------------------------
# The store
# ---------------------------------------------------------------------------


class TestHistoryStore:
    def test_query_ledgers_land_in_segments(self, session, tmp_path, monkeypatch):
        hd = os.path.join(str(tmp_path), "hist")
        monkeypatch.setenv(history.ENV_HISTORY, "1")
        monkeypatch.setenv(history.ENV_HISTORY_DIR, hd)
        t = os.path.join(str(tmp_path), "t")
        _write_table(session, t)
        df = session.read.parquet(t)
        for _ in range(3):
            df.filter(col("k") == 3).collect()
        segs = glob.glob(os.path.join(hd, "seg-*.jsonl"))
        assert len(segs) == 1  # one writer process = one owned segment
        recs = [json.loads(line) for line in open(segs[0])]
        assert len(recs) == 3
        fps = {r["fingerprint"] for r in recs}
        assert len(fps) == 1  # one plan class
        for r in recs:
            assert r["schema_version"] == history.SCHEMA_VERSION
            assert r["kind"] == "ledger"
            assert r["ledger"]["wall_s"] > 0
            assert r["ledger"]["plan_fingerprint"] == r["fingerprint"]

    def test_baselines_survive_restart(self, tmp_path, monkeypatch):
        hd = os.path.join(str(tmp_path), "hist")
        st = history.HistoryStore(hd)
        for i in range(10):
            st.record("fp-a", _ledger(wall=0.01 + i * 0.001))
        st.close()
        history.reset_stores()
        st2 = history.HistoryStore(hd)  # a fresh process would do exactly this
        bl = st2.baseline_for("fp-a")
        assert bl is not None and bl.count == 10
        assert st2.baselines()["fp-a"]["n"] == 10

    def test_segment_rotation_bounds_size(self, tmp_path, monkeypatch):
        monkeypatch.setenv(history.ENV_SEGMENT_MB, "0.000001")  # floor: 4096 B
        hd = os.path.join(str(tmp_path), "hist")
        st = history.HistoryStore(hd)
        for i in range(60):  # ~150 B/record: must cross the 4 KiB floor
            st.record("fp-a", _ledger(wall=0.01, qid=f"q{i}"))
        segs = glob.glob(os.path.join(hd, "seg-*.jsonl"))
        assert len(segs) >= 2
        assert all(os.path.getsize(p) < 3 * 4096 for p in segs)
        recs = list(history.iter_records(hd))
        assert len(recs) == 60  # rotation loses nothing
        assert metrics.counter("history.segments_rotated").value >= 1

    def test_compaction_folds_dead_writer_segments(self, tmp_path):
        hd = os.path.join(str(tmp_path), "hist")
        os.makedirs(hd)
        # A segment from a PROVABLY DEAD same-host writer (pid 2^22+9999 is
        # beyond pid_max on any default Linux) — the reclaim_orphans rule.
        dead = os.path.join(hd, "seg-localhost-4199303-deadbeef.jsonl")
        with open(dead, "w") as f:
            for i in range(12):
                rec = {
                    "schema_version": 1,
                    "kind": "ledger",
                    "ts": time.time(),
                    "fingerprint": "fp-dead",
                    "ledger": _ledger(wall=0.02, qid=f"d{i}"),
                }
                f.write(json.dumps(rec) + "\n")
        before = history.fold_baselines(history.iter_records(hd))
        # Same-host liveness keys on THIS host's name, not "localhost":
        # rename the owner to the real hostname so the pid rule applies.
        import socket

        owned = os.path.join(hd, f"seg-{socket.gethostname()}-4199303-deadbeef.jsonl")
        os.rename(dead, owned)
        st = history.HistoryStore(hd)
        folded = st.compact()
        assert folded >= 0  # may already have compacted at open
        assert not glob.glob(os.path.join(hd, "seg-*.jsonl"))
        compacts = glob.glob(os.path.join(hd, "compact-*.jsonl"))
        assert compacts
        after = history.fold_baselines(history.iter_records(hd))
        assert after["fp-dead"].count == before["fp-dead"].count == 12
        a, b = after["fp-dead"].summary(), before["fp-dead"].summary()
        assert a["wall_p50_s"] == b["wall_p50_s"]
        assert a["wall_total_s"] == pytest.approx(b["wall_total_s"], rel=1e-6)

    def test_live_writer_segments_never_claimed_even_past_ttl(
        self, tmp_path, monkeypatch
    ):
        import socket

        monkeypatch.setenv(history.ENV_TTL_S, "1")  # aggressive TTL
        hd = os.path.join(str(tmp_path), "hist")
        os.makedirs(hd)
        # A segment owned by a LIVE same-host writer (this very process),
        # aged far past the TTL: liveness must win — claiming it would lose
        # every record the writer flushes after the rename.
        live = os.path.join(
            hd, f"seg-{socket.gethostname()}-{os.getpid()}-aaaaaaaa.jsonl"
        )
        with open(live, "w") as f:
            f.write(
                json.dumps(
                    {
                        "schema_version": 1,
                        "kind": "ledger",
                        "fingerprint": "fp-live",
                        "ledger": _ledger(),
                    }
                )
                + "\n"
            )
        old = time.time() - 3600
        os.utime(live, (old, old))
        st = history.HistoryStore(hd)
        assert st.compact() == 0
        assert os.path.exists(live)

    def test_failed_compact_commit_releases_claims(self, tmp_path, monkeypatch):
        import socket

        hd = os.path.join(str(tmp_path), "hist")
        os.makedirs(hd)
        dead = os.path.join(
            hd, f"seg-{socket.gethostname()}-4199303-deadbeef.jsonl"
        )
        with open(dead, "w") as f:
            f.write(
                json.dumps(
                    {
                        "schema_version": 1,
                        "kind": "ledger",
                        "fingerprint": "fp-d",
                        "ledger": _ledger(),
                    }
                )
                + "\n"
            )
        st = history.HistoryStore(hd, load=False, compact_on_open=False)

        def boom(src, dst):
            raise OSError("disk full")

        monkeypatch.setattr(history.os, "replace", boom)
        assert st.compact() == 0
        monkeypatch.undo()
        # The claim was RELEASED back to its original name: still visible to
        # readers and still compactable (a live-pid claim would hide it).
        assert os.path.exists(dead)
        assert len(list(history.iter_records(hd))) == 1
        assert st.compact() == 1  # and the retry succeeds

    def test_compactor_crash_after_commit_never_double_counts(self, tmp_path):
        import shutil
        import socket

        hd = os.path.join(str(tmp_path), "hist")
        os.makedirs(hd)
        seg_name = f"seg-{socket.gethostname()}-4199303-deadbeef.jsonl"
        seg = os.path.join(hd, seg_name)
        with open(seg, "w") as f:
            for i in range(10):
                f.write(
                    json.dumps(
                        {
                            "schema_version": 1,
                            "kind": "ledger",
                            "fingerprint": "fp-m",
                            "ledger": _ledger(wall=0.02, qid=f"m{i}"),
                        }
                    )
                    + "\n"
                )
        backup = os.path.join(str(tmp_path), "backup.jsonl")
        shutil.copy(seg, backup)
        st = history.HistoryStore(hd, load=False, compact_on_open=False)
        assert st.compact() == 1
        assert history.fold_baselines(history.iter_records(hd))["fp-m"].count == 10
        # Simulate the compactor dying AFTER the checkpoint commit but
        # BEFORE the claim unlink: the orphaned claim reappears with a dead
        # claimant pid — its root is in the committed manifest, so readers
        # must skip it (no double count) and compaction must GC it.
        stale_claim = os.path.join(
            hd, f"{history.CLAIMED_PREFIX}{socket.gethostname()}~4199303~{seg_name}"
        )
        shutil.copy(backup, stale_claim)
        assert history.fold_baselines(history.iter_records(hd))["fp-m"].count == 10
        assert st.compact() >= 1  # the garbage claim is collected
        assert not os.path.exists(stale_claim)
        assert history.fold_baselines(history.iter_records(hd))["fp-m"].count == 10

    def test_foreign_host_claims_use_ttl_not_local_pids(self, tmp_path, monkeypatch):
        hd = os.path.join(str(tmp_path), "hist")
        os.makedirs(hd)
        # Claimant pid 1 is ALIVE on this host — but the claim came from
        # another host, where that pid number means nothing. Fresh: treated
        # as live (invisible, not compactable). TTL-aged: orphaned.
        claim = os.path.join(
            hd, f"{history.CLAIMED_PREFIX}otherhost~1~seg-otherhost-1-x.jsonl"
        )
        with open(claim, "w") as f:
            f.write(
                json.dumps(
                    {
                        "schema_version": 1,
                        "kind": "ledger",
                        "fingerprint": "fp-f",
                        "ledger": _ledger(),
                    }
                )
                + "\n"
            )
        assert history.fold_baselines(history.iter_records(hd)) == {}
        monkeypatch.setenv(history.ENV_TTL_S, "1")
        old = time.time() - 3600
        os.utime(claim, (old, old))
        assert history.fold_baselines(history.iter_records(hd))["fp-f"].count == 1
        st = history.HistoryStore(hd, load=False, compact_on_open=False)
        assert st.compact() == 1

    def test_merge_state_malformed_fields_no_raise_no_partial_merge(self):
        h = metrics.Histogram("fc")
        h.observe(0.01)
        snap = h.summary()
        h.merge_state({"count": 1, "total": 0.1, "min": "oops", "max": 2})
        # min/max garbage is dropped, numerics still fold:
        assert h.count == 2 and h.total == pytest.approx(0.11)
        h2 = metrics.Histogram("fc2")
        h2.observe(0.01)
        h2.merge_state({"count": 5, "total": "bad", "buckets": {"3": 5}})
        assert h2.summary() == snap  # nothing half-merged
        h2.merge_state("not-a-dict")
        assert h2.summary() == snap
        # A checkpoint with buckets but NO extrema (forward-compat allows
        # it) must still quantile/summarize without raising.
        h3 = metrics.Histogram("fc3")
        h3.merge_state({"count": 5, "total": 1.0, "buckets": {"10": 5}})
        assert h3.quantile(0.5) is not None
        assert h3.summary()["p99"] is not None

    def test_torn_trailing_line_is_skipped(self, tmp_path):
        hd = os.path.join(str(tmp_path), "hist")
        os.makedirs(hd)
        seg = os.path.join(hd, "seg-h-1-x.jsonl")
        good = {
            "schema_version": 1,
            "kind": "ledger",
            "fingerprint": "fp",
            "ledger": _ledger(),
        }
        with open(seg, "w") as f:
            f.write(json.dumps(good) + "\n")
            f.write(json.dumps(good) + "\n")
            f.write('{"schema_version": 1, "kind": "led')  # SIGKILL mid-append
        torn0 = metrics.counter("history.torn_lines").value
        recs = list(history.iter_file_records(seg))
        assert len(recs) == 2
        # Plain reader passes never tick the counter (re-reads of one old
        # tear must not look like fresh corruption to a monitor)...
        assert metrics.counter("history.torn_lines").value == torn0
        # ... the store's own load pass counts it exactly once.
        history.HistoryStore(hd, compact_on_open=False).close()
        assert metrics.counter("history.torn_lines").value == torn0 + 1

    def test_forward_compat_unknown_keys_and_kinds(self, tmp_path):
        hd = os.path.join(str(tmp_path), "hist")
        os.makedirs(hd)
        seg = os.path.join(hd, "seg-h-1-x.jsonl")
        with open(seg, "w") as f:
            # A record from a FUTURE writer: newer version, unknown kind,
            # extra keys everywhere — must parse, fold what's known, skip
            # the rest (never raise).
            f.write(
                json.dumps(
                    {
                        "schema_version": 99,
                        "kind": "hologram",
                        "fingerprint": "fp-x",
                        "novel_key": {"deep": [1, 2]},
                    }
                )
                + "\n"
            )
            f.write(
                json.dumps(
                    {
                        "schema_version": 99,
                        "kind": "ledger",
                        "fingerprint": "fp-x",
                        "future_field": True,
                        "ledger": dict(_ledger(wall=0.05), exotic=123),
                    }
                )
                + "\n"
            )
        folded = history.fold_baselines(history.iter_records(hd))
        assert folded["fp-x"].count == 1  # the ledger folded, the hologram didn't
        assert folded["fp-x"].summary()["wall_total_s"] == pytest.approx(0.05)


# ---------------------------------------------------------------------------
# Anomaly flagging
# ---------------------------------------------------------------------------


class TestAnomalies:
    def test_nsigma_flagging_counter_and_warn_once(self, tmp_path):
        hd = os.path.join(str(tmp_path), "hist")
        st = history.HistoryStore(hd)
        for i in range(10):
            assert st.record("fp-a", _ledger(wall=0.02 + 0.001 * (i % 3))) is None
        n0 = metrics.counter("history.anomalies").value
        with pytest.warns(RuntimeWarning, match="over its baseline"):
            verdict = st.record("fp-a", _ledger(wall=1.0))
        assert verdict is not None
        assert verdict["wall_s"] == 1.0
        assert verdict["baseline_n"] == 10
        assert metrics.counter("history.anomalies").value == n0 + 1
        # Second anomaly in the same class: counted, but silent.
        import warnings as _warnings

        with _warnings.catch_warnings():
            _warnings.simplefilter("error")
            assert st.record("fp-a", _ledger(wall=1.2)) is not None
        assert metrics.counter("history.anomalies").value == n0 + 2

    def test_young_or_fast_classes_never_flag(self, tmp_path):
        st = history.HistoryStore(os.path.join(str(tmp_path), "hist"))
        for i in range(history.ANOMALY_MIN_SAMPLES - 1):
            st.record("fp-y", _ledger(wall=0.02))
        # Baseline still too young:
        assert st.record("fp-y", _ledger(wall=5.0)) is None
        # Sub-floor walls never flag however extreme the ratio:
        for i in range(20):
            st.record("fp-z", _ledger(wall=0.0001))
        assert st.record("fp-z", _ledger(wall=0.004)) is None

    def test_anomaly_rides_root_attr_and_exporter_frame(
        self, tmp_path, monkeypatch
    ):
        hd = os.path.join(str(tmp_path), "hist")
        monkeypatch.setenv(history.ENV_HISTORY, "1")
        monkeypatch.setenv(history.ENV_HISTORY_DIR, hd)

        class FakeRoot:
            attrs: dict = {}

            def set_attr(self, k, v):
                self.attrs[k] = v

        root = FakeRoot()
        for i in range(10):
            history.land(_ledger(wall=0.02, plan_fingerprint="fp-e"), root)
        assert "history_anomaly" not in root.attrs
        with pytest.warns(RuntimeWarning):
            history.land(_ledger(wall=2.0, plan_fingerprint="fp-e"), root)
        assert root.attrs["history_anomaly"]["fingerprint"] == "fp-e"
        frame = history.frame_summary()
        assert frame["records_written"] == 11
        assert frame["anomalies_total"] >= 1
        assert any(a["fingerprint"] == "fp-e" for a in frame["anomalies"])
        # Drained: the next frame carries no stale anomalies.
        assert "anomalies" not in (history.frame_summary() or {})


# ---------------------------------------------------------------------------
# Concurrency + crash safety
# ---------------------------------------------------------------------------


_WRITER_CHILD = """
import os, sys, time
sys.path.insert(0, {repo!r})
os.environ["HYPERSPACE_HISTORY"] = "1"
os.environ["HYPERSPACE_HISTORY_DIR"] = {hd!r}
from hyperspace_tpu.telemetry import history
st = history.get_store()
n = int(sys.argv[1]) if len(sys.argv) > 1 else 50
i = 0
while n < 0 or i < n:   # n<0 = run until killed (the SIGKILL harness)
    st.record("fp-proc", {{"query_id": f"{{os.getpid()}}-{{i}}",
                           "name": "query:collect", "wall_s": 0.02,
                           "rows_produced": 1}})
    i += 1
print("WROTE", i, flush=True)
"""


class TestConcurrentAppends:
    def test_threads_lose_nothing(self, tmp_path):
        st = history.HistoryStore(os.path.join(str(tmp_path), "hist"))
        n_threads, per = 8, 50

        def worker(ti):
            for i in range(per):
                st.record("fp-t", _ledger(qid=f"t{ti}-{i}"))

        threads = [threading.Thread(target=worker, args=(ti,)) for ti in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert st.records_written == n_threads * per
        recs = [r for r in history.iter_records(st.dir) if r.get("kind") == "ledger"]
        qids = [r["ledger"]["query_id"] for r in recs]
        assert len(qids) == n_threads * per
        assert len(set(qids)) == n_threads * per  # no lost, no duplicated

    def test_two_processes_one_dir_occ_consistent(self, tmp_path):
        hd = os.path.join(str(tmp_path), "hist")
        script = _WRITER_CHILD.format(repo=REPO, hd=hd)
        procs = [
            subprocess.Popen(
                [sys.executable, "-c", script, "40"],
                stdout=subprocess.PIPE,
                stderr=subprocess.PIPE,
            )
            for _ in range(2)
        ]
        st = history.get_store(hd)  # this process writes concurrently too
        for i in range(40):
            st.record("fp-proc", _ledger(qid=f"parent-{i}"))
        for p in procs:
            out, err = p.communicate(timeout=120)
            assert p.returncode == 0, err.decode()
            assert b"WROTE 40" in out
        recs = [r for r in history.iter_records(hd) if r.get("kind") == "ledger"]
        qids = [r["ledger"]["query_id"] for r in recs]
        assert len(qids) == 120 and len(set(qids)) == 120
        # Three writers → three distinct owned segments (plus rotations).
        owners = {
            os.path.basename(p).rsplit("-", 2)[1]
            for p in glob.glob(os.path.join(hd, "seg-*.jsonl"))
        }
        assert len(owners) == 3

    def test_sigkill_mid_append_keeps_segments_parseable(self, tmp_path):
        hd = os.path.join(str(tmp_path), "hist")
        script = _WRITER_CHILD.format(repo=REPO, hd=hd)
        proc = subprocess.Popen(
            [sys.executable, "-c", script, "-1"],
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
        )
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            segs = glob.glob(os.path.join(hd, "seg-*.jsonl"))
            if segs and sum(os.path.getsize(p) for p in segs) > 20000:
                break
            time.sleep(0.05)
        os.kill(proc.pid, signal.SIGKILL)  # mid-append, no cleanup
        proc.wait()
        recs = [r for r in history.iter_records(hd) if r.get("kind") == "ledger"]
        assert len(recs) > 50  # the committed prefix survived
        # The dead writer's segment is compactable by the next store.
        st = history.HistoryStore(hd)
        assert not glob.glob(os.path.join(hd, "seg-*.jsonl"))
        after = history.fold_baselines(history.iter_records(hd))
        assert after["fp-proc"].count == len(recs)
        st.close()


# ---------------------------------------------------------------------------
# Zero cost when off
# ---------------------------------------------------------------------------


class TestZeroCostOff:
    def test_no_fingerprint_no_files_when_everything_off(
        self, session, tmp_path, monkeypatch
    ):
        for k in (
            history.ENV_HISTORY,
            history.ENV_HISTORY_DIR,
            accounting.ENV_ACCOUNTING,
            tracing.ENV_TRACE_FILE,
            tracing.ENV_TRACING,
        ):
            monkeypatch.delenv(k, raising=False)

        def boom(*a, **kw):  # the zero-cost contract: never even computed
            raise AssertionError("plan_fingerprint computed with sinks off")

        monkeypatch.setattr(fngr, "plan_fingerprint", boom)
        t = os.path.join(str(tmp_path), "t")
        _write_table(session, t)
        before = len(accounting.recent_ledgers())
        out = session.read.parquet(t).filter(col("k") == 3).collect()
        assert out.num_rows == 1
        assert len(accounting.recent_ledgers()) == before
        assert not os.path.exists(os.path.join(str(tmp_path), ".hyperspace_history"))

    def test_history_flag_alone_enables_ledgers(self, session, tmp_path, monkeypatch):
        hd = os.path.join(str(tmp_path), "hist")
        monkeypatch.setenv(history.ENV_HISTORY, "1")
        monkeypatch.setenv(history.ENV_HISTORY_DIR, hd)
        t = os.path.join(str(tmp_path), "t")
        _write_table(session, t)
        # The history deque is bounded (32): compare identities, not length.
        before_ids = {led.query_id for led in accounting.recent_ledgers()}
        session.read.parquet(t).filter(col("k") == 3).collect()
        newest = accounting.recent_ledgers()[-1]
        assert newest.query_id not in before_ids  # a fresh ledger opened
        assert glob.glob(os.path.join(hd, "seg-*.jsonl"))


# ---------------------------------------------------------------------------
# Sink rotation (trace + metrics JSONL)
# ---------------------------------------------------------------------------


class TestSinkRotation:
    def test_trace_file_rotates_and_every_file_parses(
        self, session, tmp_path, monkeypatch
    ):
        path = os.path.join(str(tmp_path), "trace.jsonl")
        monkeypatch.setenv(tracing.ENV_TRACE_FILE, path)
        monkeypatch.setenv("HYPERSPACE_TRACE_MAX_MB", "0.002")  # 2 kB
        t = os.path.join(str(tmp_path), "t")
        _write_table(session, t)
        r0 = metrics.counter("telemetry.sink.rotations").value
        df = session.read.parquet(t)
        for _ in range(6):
            df.filter(col("k") == 3).collect()
        assert metrics.counter("telemetry.sink.rotations").value > r0
        rotated = sorted(glob.glob(path + ".*"))
        assert rotated  # at least one rotated generation
        for p in [path] + rotated:
            spans = [json.loads(line) for line in open(p)]
            assert spans and all("query_id" in s for s in spans)

    def test_metrics_rotation_keeps_final_frame(self, tmp_path, monkeypatch):
        path = os.path.join(str(tmp_path), "m.jsonl")
        monkeypatch.setenv("HYPERSPACE_METRICS_MAX_MB", "0.01")  # 10 kB
        monkeypatch.setenv("HYPERSPACE_SINK_KEEP", "2")
        ex = exporter.MetricsExporter(path, interval_s=0.01).start()
        try:
            deadline = time.monotonic() + 20
            while time.monotonic() < deadline and not glob.glob(path + ".*"):
                time.sleep(0.02)
        finally:
            ex.stop()
        assert glob.glob(path + ".*"), "no rotation happened"
        frames = [json.loads(line) for line in open(path)]
        assert frames[-1]["final"] is True  # the last line survived rotation
        assert frames[-1]["schema_version"] == exporter.SCHEMA_VERSION
        # keep-N honored: generations beyond .2 never exist.
        assert not os.path.exists(path + ".3")


# ---------------------------------------------------------------------------
# SLO monitor + lane visibility
# ---------------------------------------------------------------------------


class TestSLO:
    def test_compliance_and_burn(self, monkeypatch):
        monkeypatch.setenv(slo.ENV_INTERACTIVE_P99_MS, "100")
        slo.reset()
        for _ in range(8):
            slo.observe("interactive", 0.05, tenant="a")
        for _ in range(2):
            slo.observe("interactive", 0.5, tenant="a")
        s = slo.summary()["interactive"]
        assert s["total"] == 10 and s["violations"] == 2
        assert s["compliance"] == pytest.approx(0.8)
        # 20% error rate against a 1% budget = burn 20x over the window.
        assert s["burn_5m"] == pytest.approx(20.0, rel=0.01)
        assert s["tenants"]["a"]["violations"] == 2
        text = exporter.prometheus_text()
        assert 'hyperspace_slo_compliance{lane="interactive"}' in text
        assert 'hyperspace_slo_burn_5m{lane="interactive"}' in text

    def test_fast_burn_warns_once(self, monkeypatch):
        monkeypatch.setenv(slo.ENV_BATCH_P99_MS, "1")
        slo.reset()
        n0 = metrics.counter("slo.fast_burn_alerts").value
        with pytest.warns(RuntimeWarning, match="fast-burning"):
            for _ in range(slo.FAST_BURN_MIN_EVENTS + 5):
                slo.observe("batch", 0.5)
        assert metrics.counter("slo.fast_burn_alerts").value > n0
        import warnings as _warnings

        with _warnings.catch_warnings():
            _warnings.simplefilter("error")
            slo.observe("batch", 0.5)  # warned once; further alerts silent

    def test_served_queries_feed_lane_metrics_and_ledger_lane(
        self, session, tmp_path, monkeypatch
    ):
        from hyperspace_tpu.serve import QueryServer

        monkeypatch.setenv(accounting.ENV_ACCOUNTING, "1")
        slo.reset()
        t = os.path.join(str(tmp_path), "t")
        _write_table(session, t)
        df = session.read.parquet(t)
        h0 = metrics.histogram("latency.serve.interactive").count
        b0 = metrics.histogram("latency.serve.batch").count
        with QueryServer(max_concurrent=2) as srv:
            srv.run(lambda: df.filter(col("k") == 3).collect(), lane="interactive")
            srv.run(lambda: df.group_by("grp").agg(n=("v", "count")).collect())
        assert metrics.histogram("latency.serve.interactive").count == h0 + 1
        assert metrics.histogram("latency.serve.batch").count == b0 + 1
        assert metrics.gauge("serve.inflight.interactive").value == 0
        assert metrics.gauge("serve.inflight.batch").value == 0
        lanes = {led.lane for led in accounting.recent_ledgers()[-2:]}
        assert lanes == {"interactive", "batch"}
        assert set(slo.summary()) >= {"interactive", "batch"}

    def test_failed_queries_burn_the_error_budget(self, session, monkeypatch):
        from hyperspace_tpu.serve import QueryServer

        slo.reset()
        with QueryServer(max_concurrent=2) as srv:
            fut = srv.submit(
                lambda: (_ for _ in ()).throw(RuntimeError("outage")),
                lane="interactive",
            )
            with pytest.raises(RuntimeError):
                fut.result(30)
        s = slo.summary()["interactive"]
        # A 1 ms failure is NOT compliance — the budget burns on errors too.
        assert s["total"] == 1 and s["violations"] == 1

    def test_failure_status_lands_on_ledger_and_offline_compliance(self):
        with pytest.raises(RuntimeError):
            with accounting.ledger_scope("q-fail", "query:collect"):
                raise RuntimeError("boom")
        led = accounting.recent_ledgers()[-1]
        assert led.get("status") == "error"
        # The offline view judges recorded failures like the live monitor:
        # a 1 ms errored query violates regardless of the objective.
        c = slo.compliance_over(
            [
                {"lane": "interactive", "wall_s": 0.001, "status": "error"},
                {"lane": "interactive", "wall_s": 0.001},
            ]
        )
        assert c["interactive"]["total"] == 2
        assert c["interactive"]["violations"] == 1

    def test_serial_fallback_observes_slo_too(self, session, tmp_path, monkeypatch):
        from hyperspace_tpu.serve import QueryServer

        monkeypatch.setenv("HYPERSPACE_SERVING", "0")
        slo.reset()
        t = os.path.join(str(tmp_path), "t")
        _write_table(session, t)
        df = session.read.parquet(t)
        with QueryServer() as srv:
            srv.run(lambda: df.filter(col("k") == 1).collect(), lane="interactive")
        s = slo.summary()
        assert s["interactive"]["total"] == 1


# ---------------------------------------------------------------------------
# Tools: hsreport + bench_compare --history
# ---------------------------------------------------------------------------


def _seed_store(hd, fast_n=10, slow_n=0, lane=None, fp="fp-tool", wall=0.02):
    st = history.HistoryStore(hd, compact_on_open=False)
    for i in range(fast_n):
        st.record(
            fp,
            _ledger(wall=wall, qid=f"f{i}", lane=lane, bytes_decoded=1000,
                    xla_compiles=2, io_retries=1),
        )
    for i in range(slow_n):
        st.record(fp, _ledger(wall=wall * 10, qid=f"s{i}", lane=lane))
    st.close()
    history.reset_stores()
    return hd


class TestTools:
    def test_hsreport_renders_all_sections(self, tmp_path, capsys):
        hs = _tool("hsreport")
        hd = os.path.join(str(tmp_path), "hist")
        _seed_store(hd, fast_n=12, lane="interactive")
        assert hs.main([hd]) == 0
        out = capsys.readouterr().out
        assert "top plan classes by total cost" in out
        assert "SLO compliance" in out
        assert "compile-storm hotspots" in out
        assert "io-retry hotspots" in out
        assert "fp-tool" in out

    def test_hsreport_json_and_compare_gate(self, tmp_path, capsys):
        hs = _tool("hsreport")
        a = _seed_store(os.path.join(str(tmp_path), "a"), fast_n=12, wall=0.05)
        b = _seed_store(os.path.join(str(tmp_path), "b"), fast_n=12, wall=0.25)
        assert hs.main([a, "--json"]) == 0
        report = json.loads(capsys.readouterr().out)
        assert report["fingerprints"] == 1
        assert report["top_classes"][0]["n"] == 12
        # b is 5x slower on the same class: the compare gate must fail...
        assert hs.main([a, "--compare", b]) == 1
        capsys.readouterr()
        # ... and the improving direction passes.
        assert hs.main([b, "--compare", a]) == 0
        capsys.readouterr()
        # A store that merely recorded MORE traffic at the same latency is
        # not a regression (cumulative wall_total_s must not gate).
        c = _seed_store(os.path.join(str(tmp_path), "c"), fast_n=40, wall=0.05)
        assert hs.main([a, "--compare", c]) == 0

    def test_bench_compare_history_gate(self, tmp_path, capsys):
        with pytest.warns(RuntimeWarning):
            # The slow recent window itself trips the anomaly warn-once.
            regressed = _seed_store(
                os.path.join(str(tmp_path), "reg"), fast_n=12, slow_n=5, wall=0.05
            )
        healthy = _seed_store(
            os.path.join(str(tmp_path), "ok"), fast_n=17, wall=0.05
        )
        bc = _tool("bench_compare")
        assert bc.main(["--history", regressed]) == 1
        assert "REGRESSION" in capsys.readouterr().out
        assert bc.main(["--history", healthy]) == 0
        # Static pair + history compose (the "in addition to" contract).
        a = os.path.join(str(tmp_path), "a.json")
        b = os.path.join(str(tmp_path), "b.json")
        json.dump({"q_p50_s": 1.0}, open(a, "w"))
        json.dump({"q_p50_s": 1.0}, open(b, "w"))
        assert bc.main([a, b, "--history", regressed]) == 1
        assert bc.main([a, b, "--history", healthy]) == 0
        # A wrong/missing dir must FAIL the gate loudly, never pass green.
        capsys.readouterr()
        assert bc.main(["--history", os.path.join(str(tmp_path), "nope")]) == 2
        # One positional alone (candidate forgotten) is a usage error, not a
        # silent skip of the static gate.
        with pytest.raises(SystemExit) as e:
            bc.main([a, "--history", healthy])
        assert e.value.code == 2


# ---------------------------------------------------------------------------
# Histogram state roundtrip (the baseline serialization primitive)
# ---------------------------------------------------------------------------


def test_histogram_state_roundtrip_preserves_summary():
    h = metrics.Histogram("rt")
    for i in range(200):
        h.observe(0.001 * (i + 1))
    clone = metrics.Histogram("rt2")
    clone.merge_state(json.loads(json.dumps(h.dump_state())))
    assert clone.summary() == h.summary()
    # Merging is additive (two halves == the whole).
    a, b = metrics.Histogram("a"), metrics.Histogram("b")
    for i in range(100):
        a.observe(0.001 * (i + 1))
    for i in range(100, 200):
        b.observe(0.001 * (i + 1))
    merged = metrics.Histogram("m")
    merged.merge_state(a.dump_state())
    merged.merge_state(b.dump_state())
    assert merged.summary() == h.summary()
