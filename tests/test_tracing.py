"""Unified query-lifecycle tracing: span tree, JSONL export, metrics registry,
EXPLAIN ANALYZE.

Pins the observability contracts:
- One indexed streamed join→aggregate query = ONE span tree (one query_id)
  covering plan → rule → join stages (probe/verify/gather/…) → aggregate,
  with non-negative durations and parent linkage that resolves, exported as
  JSONL via ``HYPERSPACE_TRACE_FILE`` (the schema the CI smoke leg checks).
- `explain(analyze=True)` renders the SAME tree with measured wall times,
  row counts, cache-hit annotations and the rule decisions.
- Telemetry concurrency: span creation and metric increments hammered from a
  thread pool lose nothing; a crashing worker closes its span with error
  status; trace history is bounded (deque(maxlen=16)).
- `EventLoggerFactory` falls back to NoOpEventLogger (cached, one warning)
  on a bad dotted path instead of raising mid-query.
"""

import json
import os
import threading
from concurrent.futures import ThreadPoolExecutor

import numpy as np
import pytest

from hyperspace_tpu import IndexConfig, IndexConstants
from hyperspace_tpu.engine import HyperspaceSession, col
from hyperspace_tpu.hyperspace import Hyperspace, enable_hyperspace
from hyperspace_tpu.telemetry import metrics, tracing


@pytest.fixture()
def session(tmp_path):
    base = str(tmp_path)
    s = HyperspaceSession(warehouse=base)
    s.conf.set(IndexConstants.INDEX_SYSTEM_PATH, os.path.join(base, "indexes"))
    s.conf.set(IndexConstants.INDEX_NUM_BUCKETS, 8)
    return s


def _indexed_join_agg(s, tmp_path):
    """Two indexed sides + a streamed join→aggregate query over them."""
    rng = np.random.RandomState(7)
    n = 8000
    li = os.path.join(str(tmp_path), "li")
    orr = os.path.join(str(tmp_path), "orders")
    s.write_parquet(
        {
            "lk": rng.randint(0, 300, n).astype(np.int64),
            "v": rng.randint(1, 99, n).astype(np.int64),
        },
        li,
    )
    s.write_parquet(
        {
            "ok": np.arange(300, dtype=np.int64),
            "w": rng.randint(1, 9, 300).astype(np.int64),
        },
        orr,
    )
    hs = Hyperspace(s)
    hs.create_index(s.read.parquet(li), IndexConfig("liIdx", ["lk"], ["v"]))
    hs.create_index(s.read.parquet(orr), IndexConfig("orIdx", ["ok"], ["w"]))
    enable_hyperspace(s)

    def q():
        return (
            s.read.parquet(li)
            .join(s.read.parquet(orr), col("lk") == col("ok"))
            .with_column("rev", col("v") * col("w"))
            .group_by("lk")
            .agg(total=("rev", "sum"))
        )

    return q


def _check_jsonl_schema(spans):
    """The CI smoke leg's schema contract: every span has a query_id, a
    non-negative duration, and a parent that resolves within its trace."""
    assert spans, "trace file is empty"
    ids = {(sp["query_id"], sp["span_id"]) for sp in spans}
    for sp in spans:
        assert sp["query_id"], sp
        assert isinstance(sp["span_id"], int), sp
        assert sp["duration_s"] is not None and sp["duration_s"] >= 0, sp
        assert sp["status"] in ("ok", "error", "unclosed"), sp
        if sp["parent_id"] is not None:
            assert (sp["query_id"], sp["parent_id"]) in ids, sp


def test_indexed_join_agg_single_span_tree(session, tmp_path, monkeypatch):
    # Pin the STREAMED bucket-join executor (the acceptance shape): under
    # HYPERSPACE_FORCE_DEVICE_OPS=1 the fused device join→aggregate wins the
    # dispatch and runs as one program with no stage summaries to bridge.
    monkeypatch.setenv("HYPERSPACE_FORCE_DEVICE_OPS", "0")
    monkeypatch.setenv("HYPERSPACE_QUERY_STREAMING", "1")
    q = _indexed_join_agg(session, tmp_path)
    trace_file = os.path.join(str(tmp_path), "trace.jsonl")
    monkeypatch.setenv(tracing.ENV_TRACE_FILE, trace_file)
    out = q().collect()
    assert out.num_rows == 300
    monkeypatch.delenv(tracing.ENV_TRACE_FILE)

    spans = [json.loads(line) for line in open(trace_file)]
    _check_jsonl_schema(spans)
    # ONE query = ONE query_id across every exported span.
    assert len({sp["query_id"] for sp in spans}) == 1
    names = {sp["name"] for sp in spans}
    assert "query:collect" in names and "plan" in names
    assert "op:HashAggregate" in names
    assert "rule:JoinIndexRule" in names
    # The streamed bucketed join's stage spans: probe/verify/gather at
    # minimum (cold run), riding the join:stages summary span.
    assert "join:stages" in names
    for stage in ("join:probe", "join:verify", "join:gather"):
        assert stage in names, sorted(names)
    # The root is the only parentless span.
    roots = [sp for sp in spans if sp["parent_id"] is None]
    assert len(roots) == 1 and roots[0]["name"] == "query:collect"
    assert roots[0]["attrs"].get("rows_out") == 300
    # The rule span carries the applied decision with the index names.
    rule = next(sp for sp in spans if sp["name"] == "rule:JoinIndexRule")
    decisions = rule["attrs"]["decisions"]
    assert any(
        d["applied"] and d["indexes"] == ["liIdx", "orIdx"] for d in decisions
    )


def test_explain_analyze_annotates_measured_tree(session, tmp_path):
    q = _indexed_join_agg(session, tmp_path)
    q().collect()  # warm: analyze output must reflect cache hits honestly
    s = q().explain(analyze=True)
    assert "EXPLAIN ANALYZE" in s
    assert "query_id=" in s and "wall=" in s
    assert "HashAggregate" in s and "SortMergeJoin" in s
    assert "rows=300" in s
    assert "JoinIndexRule: applied" in s
    assert "liIdx" in s and "orIdx" in s
    assert "bucketed_cache=hit" in s  # warm run reads the concat cache
    assert "Cache/metric deltas" in s
    # The redirect form matches the Hyperspace facade form.
    captured = []
    Hyperspace(session).explain(q(), analyze=True, redirect=captured.append)
    assert captured and "EXPLAIN ANALYZE" in captured[0]


def test_explain_analyze_plain_query(session, tmp_path):
    path = os.path.join(str(tmp_path), "t")
    session.write_parquet(
        {"k": np.arange(100, dtype=np.int64), "v": np.arange(100, dtype=np.int64)},
        path,
    )
    df = (
        session.read.parquet(path)
        .filter(col("k") < 50)
        .group_by("k")
        .agg(total=("v", "sum"))
    )
    s = df.explain(analyze=True)
    assert "HashAggregate" in s and "Filter" in s and "Scan" in s
    assert "rows=50" in s
    # analyze=False returns the plain static tree.
    assert df.explain() == df.explain_string()


def test_tracing_disabled_records_nothing(session, tmp_path, monkeypatch):
    monkeypatch.delenv(tracing.ENV_TRACE_FILE, raising=False)
    monkeypatch.delenv(tracing.ENV_TRACING, raising=False)
    path = os.path.join(str(tmp_path), "t")
    session.write_parquet({"k": np.arange(10, dtype=np.int64)}, path)
    before = len(tracing.recent_traces())
    session.read.parquet(path).collect()
    assert len(tracing.recent_traces()) == before
    # The hooks hand out the shared no-op span.
    with tracing.query_span("query:off") as sp:
        assert sp is tracing.NOOP_SPAN


def test_span_concurrency_and_error_status():
    """Pool-worker-shaped hammer: child spans created from many threads under
    one root all register under one trace; a crashing worker's span closes
    with error status before the exception propagates."""
    n_threads, n_tasks = 8, 200

    with tracing.capture() as cap:
        with tracing.query_span("query:hammer") as root:

            def work(i):
                if i == 137:
                    with pytest.raises(RuntimeError):
                        with tracing.span(f"w{i}", parent=root):
                            raise RuntimeError("worker died")
                    return
                with tracing.span(f"w{i}", parent=root) as sp:
                    sp.set_attr("i", i)

            with ThreadPoolExecutor(max_workers=n_threads) as pool:
                list(pool.map(work, range(n_tasks)))

    trace = cap.trace
    assert trace is not None
    workers = [s for s in trace.spans if s.name.startswith("w")]
    assert len(workers) == n_tasks  # no lost spans
    assert all(s.parent_id == trace.root.span_id for s in workers)
    assert all(s.duration_s is not None and s.duration_s >= 0 for s in workers)
    crashed = [s for s in trace.spans if s.name == "w137"]
    assert len(crashed) == 1 and crashed[0].status == "error"
    assert "worker died" in crashed[0].attrs["error"]
    ok = [s for s in workers if s.name != "w137"]
    assert all(s.status == "ok" for s in ok)


def test_metric_increments_lose_nothing_under_threads():
    c = metrics.counter("test.hammer.counter")
    h = metrics.histogram("test.hammer.hist")
    start = c.value
    n_threads, n_inc = 16, 500

    def work(_):
        for _i in range(n_inc):
            c.inc()
            h.observe(0.5)

    with ThreadPoolExecutor(max_workers=n_threads) as pool:
        list(pool.map(work, range(n_threads)))
    assert c.value - start == n_threads * n_inc
    snap = metrics.snapshot()
    assert snap["counters"]["test.hammer.counter"] == c.value
    assert snap["histograms"]["test.hammer.hist"]["count"] >= n_threads * n_inc
    assert json.dumps(snap)  # bench_detail-serializable


def test_snapshot_hit_rates_derive_from_counter_pairs():
    metrics.counter("test.rate.hits").inc(3)
    metrics.counter("test.rate.misses").inc(1)
    snap = metrics.snapshot()
    assert snap["rates"]["test.rate"] == 0.75


def test_trace_history_is_bounded(monkeypatch):
    monkeypatch.setenv(tracing.ENV_TRACING, "1")
    for i in range(tracing._RECENT.maxlen + 5):
        with tracing.query_span(f"query:bounded{i}"):
            pass
    recent = tracing.recent_traces()
    assert len(recent) == tracing._RECENT.maxlen == 16
    # Newest last; the oldest of the burst aged out.
    assert recent[-1].root.name == f"query:bounded{tracing._RECENT.maxlen + 4}"


def test_nested_collect_stays_one_query(session, tmp_path, monkeypatch):
    """A scalar-subquery-style nested action inside a traced query attaches
    as a child span instead of opening a second query_id."""
    monkeypatch.setenv(tracing.ENV_TRACING, "1")
    path = os.path.join(str(tmp_path), "t")
    session.write_parquet({"k": np.arange(20, dtype=np.int64)}, path)
    with tracing.capture() as cap:
        with tracing.query_span("query:outer"):
            session.read.parquet(path).collect()
    trace = cap.trace
    assert trace.root.name == "query:outer"
    inner = trace.find("query:collect")
    assert len(inner) == 1 and inner[0].parent_id == trace.root.span_id


def test_stage_spans_ride_streaming_scan_aggregate(session, tmp_path, monkeypatch):
    """A multi-file streamed scan→aggregate records query:stages spans under
    its HashAggregate span (the StageTimings→span bridge)."""
    from hyperspace_tpu.engine import io as engine_io
    from hyperspace_tpu.engine.table import Table

    monkeypatch.setenv("HYPERSPACE_QUERY_STREAMING", "1")
    path = os.path.join(str(tmp_path), "multi")
    rng = np.random.RandomState(3)
    for i in range(3):
        engine_io.write_parquet(
            Table.from_pydict(
                {
                    "g": rng.randint(0, 5, 1000).astype(np.int64),
                    "x": rng.randint(0, 100, 1000).astype(np.int64),
                }
            ),
            os.path.join(path, f"part-{i:05d}.parquet"),
        )
    df = session.read.parquet(path).group_by("g").agg(total=("x", "sum"))
    with tracing.capture() as cap:
        df.collect()
    names = {s.name for s in cap.trace.spans}
    assert "query:stages" in names, sorted(names)
    assert any(n.startswith("query:") and n != "query:stages" for n in names)


def test_pallas_fallbacks_attach_to_build_and_query_summaries(monkeypatch):
    """A Pallas fallback during an operation is visible on that operation's
    build/query stage summary — as a PER-OPERATION delta, not the session
    counters (a fallback in query 1 must not reappear on every later clean
    operation's summary). Previously only record_join_stages carried any
    fallback info at all."""
    from hyperspace_tpu.ops import pallas_probe
    from hyperspace_tpu.telemetry import profiling

    counts = {"int": 3}
    monkeypatch.setattr(pallas_probe, "_fallback_counts", counts)
    st = profiling.StageTimings(mode="t")  # snapshots the baseline ({int: 3})
    st.add("decode", 0.1)
    counts["int"] = 5  # two fallbacks happen DURING the operation
    profiling.record_build_stages(st.summary())
    got = profiling.last_build_stages()
    assert got["pallas_fallbacks"]["probe"]["failures"] == {"int": 2}

    # A clean operation after the fallback latched: no fallback key at all.
    clean = profiling.StageTimings(mode="t")
    clean.add("eval", 0.1)
    profiling.record_query_stages(clean.summary())
    assert "pallas_fallbacks" not in profiling.last_query_stages()


def test_event_logger_bad_path_falls_back_to_noop():
    from hyperspace_tpu.telemetry import EventLoggerFactory, NoOpEventLogger
    from hyperspace_tpu.telemetry.events import HyperspaceEvent

    EventLoggerFactory.reset()
    try:
        logger = EventLoggerFactory.get_logger("no.such.module.NoSuchLogger")
        assert isinstance(logger, NoOpEventLogger)
        logger.log_event(HyperspaceEvent(message="must not raise"))
        # Cached: the broken import is not retried per event.
        assert EventLoggerFactory.get_logger("no.such.module.NoSuchLogger") is logger
        # A bad ATTRIBUTE on a real module falls back the same way.
        assert isinstance(
            EventLoggerFactory.get_logger("hyperspace_tpu.telemetry.NoSuchAttr"),
            NoOpEventLogger,
        )
    finally:
        EventLoggerFactory.reset()


def test_rule_skip_reasons_recorded(session, tmp_path, monkeypatch):
    """An eligible-but-unusable pattern records a skipped decision with a
    reason (here: indexes exist but none covers the query)."""
    path = os.path.join(str(tmp_path), "t")
    session.write_parquet(
        {
            "a": np.arange(50, dtype=np.int64),
            "b": np.arange(50, dtype=np.int64),
            "c": np.arange(50, dtype=np.int64),
        },
        path,
    )
    hs = Hyperspace(session)
    hs.create_index(
        session.read.parquet(path), IndexConfig("abIdx", ["a"], ["b"])
    )
    enable_hyperspace(session)
    # Filter on a non-head column: the index cannot cover the plan.
    df = session.read.parquet(path).filter(col("c") > 10).select("c")
    with tracing.capture() as cap:
        with tracing.query_span("query:skip"):
            session.optimize(df.plan)
    rule_spans = cap.trace.find("rule:FilterIndexRule")
    assert rule_spans
    decisions = rule_spans[0].attrs.get("decisions", [])
    assert any(not d["applied"] and d.get("reason") for d in decisions)


def test_traced_query_equals_untraced(session, tmp_path, monkeypatch):
    """Tracing must observe, never change: identical rows with the trace
    sink on and off."""
    q = _indexed_join_agg(session, tmp_path)
    plain = sorted(map(tuple, q().collect().rows()))
    monkeypatch.setenv(tracing.ENV_TRACE_FILE, os.path.join(str(tmp_path), "t.jsonl"))
    traced = sorted(map(tuple, q().collect().rows()))
    assert traced == plain
