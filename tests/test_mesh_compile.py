"""Mesh-path compile boundedness + the HYPERSPACE_DISTRIBUTED fallback contract.

The r05 TPU bench died inside a 2400 s compile because device-program shapes
tracked exact table sizes. The rebuilt mesh path quantizes every shape that
reaches a device program (hash row dims, exchange shard rows + capacity, probe
block widths — all pow2, floored at `mesh_row_quantum`), so each labeled
program compiles EXACTLY ONCE per workload class no matter how many builds and
queries run. These tests pin that with the compile observatory, pin
`HYPERSPACE_DISTRIBUTED=0` as a byte-identical fallback (index file bytes AND
query rows), and pin the persistent XLA compilation cache knob end to end.
"""

import hashlib
import os

import numpy as np
import pytest

from hyperspace_tpu import IndexConfig, IndexConstants
from hyperspace_tpu.engine import HyperspaceSession, col
from hyperspace_tpu.hyperspace import Hyperspace, disable_hyperspace, enable_hyperspace
from hyperspace_tpu.telemetry import compile_log, metrics

# Row counts chosen to share ONE pow2 workload class on the 8-device mesh
# (shards of 375..625 rows all quantize to the 1024-row quantum; per-cell
# exchange counts stay far below the 1024 capacity floor).
ROW_COUNTS = (3000, 4000, 5000)
NUM_BUCKETS = 24  # distinct from every other suite: fresh program shapes

MESH_LABELS = ("parallel.exchange_counts", "parallel.exchange", "parallel.probe")


def _session(tmp_path, num_buckets=NUM_BUCKETS):
    s = HyperspaceSession(warehouse=str(tmp_path))
    s.conf.set(IndexConstants.INDEX_SYSTEM_PATH, str(tmp_path / "indexes"))
    s.conf.set(IndexConstants.INDEX_NUM_BUCKETS, num_buckets)
    s.conf.set(IndexConstants.DISTRIBUTED_MIN_ROWS, 0)
    return s


def _write_pair(s, base, n, seed=3, suffix="", key_range=40):
    # key_range trades duplicate-key density (ties — the byte-identity
    # oracles want them) against bucket balance (the compile-boundedness test
    # wants every device block inside ONE pow2 class, so it spreads keys).
    rng = np.random.RandomState(seed)
    s.write_parquet(
        {
            "k": rng.randint(0, key_range, n).astype(np.int64),
            "name": np.array([f"d{i % 40}" for i in range(n)]),
        },
        os.path.join(base, f"dept{suffix}"),
    )
    s.write_parquet(
        {
            "ek": rng.randint(0, key_range, n // 4).astype(np.int64),
            "eid": np.arange(n // 4, dtype=np.int64),
        },
        os.path.join(base, f"emp{suffix}"),
    )


def _dir_hashes(root):
    return {
        f: hashlib.sha256(open(os.path.join(root, f), "rb").read()).hexdigest()
        for f in sorted(os.listdir(root))
        if f.startswith("part-")
    }


def test_mesh_programs_compile_exactly_once_across_row_counts(tmp_path, monkeypatch):
    """Builds + indexed joins at several row counts share ONE compiled program
    per mesh label: the compile observatory sees exactly one backend compile
    for each `parallel.*` program across the whole workload."""
    monkeypatch.setenv("HYPERSPACE_DISTRIBUTED", "1")
    s = _session(tmp_path)
    base = str(tmp_path)
    hs = Hyperspace(s)
    def counts():
        return {
            lbl: compile_log.program_summary().get(lbl, {}).get("compiles", 0)
            for lbl in MESH_LABELS
        }

    before = counts()
    after_first = None
    for i, n in enumerate(ROW_COUNTS):
        _write_pair(s, base, n, seed=3 + i, suffix=str(i), key_range=1000)
        d = s.read.parquet(os.path.join(base, f"dept{i}"))
        e = s.read.parquet(os.path.join(base, f"emp{i}"))
        hs.create_index(d, IndexConfig(f"dIdx{i}", ["k"], ["name"]))
        hs.create_index(e, IndexConfig(f"eIdx{i}", ["ek"], ["eid"]))
        enable_hyperspace(s)
        q = d.join(e, col("k") == col("ek")).select("name", "eid")
        assert len(q.sorted_rows()) > 0
        q.count()  # repeat query: must not add a single compile
        if after_first is None:
            after_first = counts()
    after = counts()
    for lbl in MESH_LABELS:
        assert after[lbl] >= 1, f"{lbl} never compiled (mesh path not taken?)"
        # At most ONE compile for the whole workload — zero when an earlier
        # suite in the same process already compiled this quantized shape
        # class (cross-workload program reuse is the point of the grid).
        delta = after[lbl] - before[lbl]
        assert delta <= 1, (
            f"{lbl} compiled {delta} times across row counts {ROW_COUNTS} "
            f"(quantization broken): {compile_log.program_summary().get(lbl)}"
        )
        # And EVERYTHING after the first build+query pair is compile-free.
        assert after[lbl] == after_first[lbl], (
            f"{lbl} recompiled on a later row count: "
            f"{after_first[lbl]} -> {after[lbl]}"
        )


def test_exchange_traffic_counters_tick(tmp_path, monkeypatch):
    monkeypatch.setenv("HYPERSPACE_DISTRIBUTED", "1")
    s = _session(tmp_path)
    base = str(tmp_path)
    _write_pair(s, base, 2000)
    snap0 = metrics.snapshot()["counters"]
    hs = Hyperspace(s)
    hs.create_index(
        s.read.parquet(os.path.join(base, "dept")), IndexConfig("tIdx", ["k"], ["name"])
    )
    snap = metrics.snapshot()["counters"]
    rows = snap.get("parallel.exchange.rows", 0) - snap0.get("parallel.exchange.rows", 0)
    moved = snap.get("parallel.exchange.bytes_moved", 0) - snap0.get(
        "parallel.exchange.bytes_moved", 0
    )
    payload = snap.get("parallel.exchange.bytes_payload", 0) - snap0.get(
        "parallel.exchange.bytes_payload", 0
    )
    assert rows == 2000
    assert payload > 0
    # The padded all_to_all matrix always carries at least the payload bytes.
    assert moved >= payload


class TestDistributedFlagContract:
    """HYPERSPACE_DISTRIBUTED=0 is the exact single-device fallback, in the
    standing PR-1/PR-2 env-flag oracle style."""

    def test_flag_off_disables_mesh(self, tmp_path, monkeypatch):
        monkeypatch.setenv("HYPERSPACE_DISTRIBUTED", "1")
        s = _session(tmp_path)
        assert s.mesh_for(10) is not None
        monkeypatch.setenv("HYPERSPACE_DISTRIBUTED", "0")
        assert s.mesh_for(10) is None
        monkeypatch.setenv("HYPERSPACE_DISTRIBUTED", "1")
        assert s.mesh_for(10) is not None

    def test_build_outputs_byte_identical(self, tmp_path, monkeypatch):
        monkeypatch.setenv("HYPERSPACE_DISTRIBUTED", "1")
        s = _session(tmp_path)
        base = str(tmp_path)
        _write_pair(s, base, 3000)  # duplicate keys: ties exercise the order
        hs = Hyperspace(s)
        df = s.read.parquet(os.path.join(base, "dept"))
        hs.create_index(df, IndexConfig("meshIdx", ["k"], ["name"]))
        monkeypatch.setenv("HYPERSPACE_DISTRIBUTED", "0")
        hs.create_index(df, IndexConfig("localIdx", ["k"], ["name"]))
        hm = _dir_hashes(os.path.join(base, "indexes", "meshIdx", "v__=0"))
        hl = _dir_hashes(os.path.join(base, "indexes", "localIdx", "v__=0"))
        assert list(hm) == list(hl)
        assert hm == {f.replace("local", "mesh"): h for f, h in hl.items()}

    def test_string_key_build_byte_identical(self, tmp_path, monkeypatch):
        monkeypatch.setenv("HYPERSPACE_DISTRIBUTED", "1")
        s = _session(tmp_path)
        base = str(tmp_path)
        _write_pair(s, base, 2500)
        hs = Hyperspace(s)
        df = s.read.parquet(os.path.join(base, "dept"))
        hs.create_index(df, IndexConfig("meshStr", ["name"], ["k"]))
        monkeypatch.setenv("HYPERSPACE_DISTRIBUTED", "0")
        hs.create_index(df, IndexConfig("localStr", ["name"], ["k"]))
        hm = _dir_hashes(os.path.join(base, "indexes", "meshStr", "v__=0"))
        hl = _dir_hashes(os.path.join(base, "indexes", "localStr", "v__=0"))
        assert list(hm) == list(hl) and set(hm.values()) == set(hl.values())

    def test_query_results_identical_on_and_off(self, tmp_path, monkeypatch):
        monkeypatch.setenv("HYPERSPACE_DISTRIBUTED", "1")
        s = _session(tmp_path)
        base = str(tmp_path)
        _write_pair(s, base, 3000)
        hs = Hyperspace(s)
        hs.create_index(
            s.read.parquet(os.path.join(base, "dept")),
            IndexConfig("qd", ["k"], ["name"]),
        )
        hs.create_index(
            s.read.parquet(os.path.join(base, "emp")),
            IndexConfig("qe", ["ek"], ["eid"]),
        )
        enable_hyperspace(s)

        def q():
            d = s.read.parquet(os.path.join(base, "dept"))
            e = s.read.parquet(os.path.join(base, "emp"))
            return d.join(e, col("k") == col("ek")).select("name", "eid")

        mesh_rows = q().collect().rows()  # exact rows INCLUDING order
        monkeypatch.setenv("HYPERSPACE_DISTRIBUTED", "0")
        single_rows = q().collect().rows()
        assert len(mesh_rows) > 0
        assert mesh_rows == single_rows
        # Non-indexed general join: the real exchange vs the host merge join.
        disable_hyperspace(s)
        monkeypatch.setenv("HYPERSPACE_DISTRIBUTED", "1")
        got = q().sorted_rows()
        monkeypatch.setenv("HYPERSPACE_DISTRIBUTED", "0")
        assert got == q().sorted_rows()


class TestPersistentCompileCache:
    def test_session_knob_configures_jax_and_hits_surface(self, tmp_path, monkeypatch):
        import jax
        import jax.numpy as jnp

        from hyperspace_tpu.engine import session as session_mod

        cache_dir = str(tmp_path / "xla_cache")
        monkeypatch.setenv("HYPERSPACE_COMPILE_CACHE_DIR", cache_dir)
        monkeypatch.setattr(session_mod, "_compile_cache_done", False)
        try:
            _session(tmp_path)  # session init applies the knob
            assert jax.config.jax_compilation_cache_dir == cache_dir
            f = compile_log.observed_jit(lambda x: x * 5 + 2, label="test.pcache")
            f(jnp.ones(333))
            assert os.listdir(cache_dir), "no persistent cache entries written"
            hits0 = compile_log.compile_cache_summary()["events"].get("cache_hits", 0)
            jax.clear_caches()  # drop in-memory executables: next dispatch
            f(jnp.ones(333))  # must come from the PERSISTENT cache
            summary = compile_log.compile_cache_summary()
            assert summary["dir"] == cache_dir
            assert summary["events"].get("cache_hits", 0) > hits0
        finally:
            jax.config.update("jax_compilation_cache_dir", None)

    def test_exporter_frame_carries_compile_cache(self, tmp_path, monkeypatch):
        import json

        from hyperspace_tpu.telemetry.exporter import MetricsExporter

        monkeypatch.setenv("HYPERSPACE_COMPILE_CACHE_DIR", str(tmp_path / "c"))
        path = str(tmp_path / "frames.jsonl")
        ex = MetricsExporter(path, interval_s=0.05).start()
        ex.stop()
        frames = [json.loads(l) for l in open(path)]
        assert frames and frames[-1].get("final") is True
        assert frames[-1].get("compile_cache", {}).get("dir") == str(tmp_path / "c")


def test_skewed_layout_stays_on_classed_executor(tmp_path, monkeypatch):
    """JSPIM skew guard: an outlier-heavy bucket layout skips the mesh probe
    (whose global-cap padding would multiply every device's probe area) and
    rides the PR-3 size-classed executor — with correct results either way."""
    monkeypatch.setenv("HYPERSPACE_DISTRIBUTED", "1")
    s = _session(tmp_path, num_buckets=16)
    base = str(tmp_path)
    rng = np.random.RandomState(11)
    n = 4000
    hot = rng.rand(n) < 0.6  # one 60%-hot key: a guaranteed outlier bucket
    keys = np.where(hot, 7, rng.randint(0, 500, n)).astype(np.int64)
    s.write_parquet(
        {"k": keys, "v": np.arange(n, dtype=np.int64)}, os.path.join(base, "hotL")
    )
    s.write_parquet(
        {"rk": keys[: n // 2], "w": np.arange(n // 2, dtype=np.int64)},
        os.path.join(base, "hotR"),
    )
    hs = Hyperspace(s)
    hs.create_index(
        s.read.parquet(os.path.join(base, "hotL")), IndexConfig("skL", ["k"], ["v"])
    )
    hs.create_index(
        s.read.parquet(os.path.join(base, "hotR")), IndexConfig("skR", ["rk"], ["w"])
    )
    enable_hyperspace(s)

    from hyperspace_tpu.parallel import table_ops

    calls = {"n": 0}
    real = table_ops.probe_dist_blocks

    def spy(*a, **k):
        calls["n"] += 1
        return real(*a, **k)

    monkeypatch.setattr(table_ops, "probe_dist_blocks", spy)

    def q():
        l = s.read.parquet(os.path.join(base, "hotL"))
        r = s.read.parquet(os.path.join(base, "hotR"))
        return l.join(r, col("k") == col("rk")).select("v", "w")

    got = q().sorted_rows()
    assert calls["n"] == 0, "skewed layout took the mesh probe"
    disable_hyperspace(s)
    expected = q().sorted_rows()
    assert len(got) > 0 and got == expected


def test_shim_pjit_compiles_sharded_program(tmp_path):
    """The shim's pjit entry (jax.jit on this build) accepts sharding
    annotations and runs a mesh-sharded program — the seam new sharded
    programs should be declared through."""
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from hyperspace_tpu.parallel import make_mesh, pjit
    from hyperspace_tpu.parallel.mesh import BUCKET_AXIS

    mesh = make_mesh(8)
    sh = NamedSharding(mesh, P(BUCKET_AXIS))
    f = pjit(lambda x: x * 2 + 1, in_shardings=(sh,), out_shardings=sh)
    import jax

    x = jax.device_put(jnp.arange(64, dtype=jnp.int64), sh)
    out = f(x)
    assert (np.asarray(out) == np.arange(64) * 2 + 1).all()
    assert out.sharding == sh
