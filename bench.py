"""Benchmark: covering-index build + indexed join query vs the non-indexed scan path.

Runs the BASELINE.md config-2 shape (two CoveringIndexes on TPC-H-style
lineitem/orders; bucketed sort-merge join) at a size that fits one chip, on whatever
backend jax selects (the real TPU under the driver; CPU locally).

Prints ONE JSON line:
  metric       what was measured
  value        indexed path wall-clock: index build (both sides, amortized over
               ROUNDS queries) + indexed-join p50, seconds
  unit         "s"
  vs_baseline  speedup of the indexed join query p50 over the non-indexed
               sort-merge join p50 on identical hardware (the reference's own
               headline mechanism: shuffle elimination; north star is 5x)
"""

import json
import os
import shutil
import sys
import tempfile
import time

import numpy as np


def _ensure_live_backend(timeouts_s=(60, 180)) -> dict:
    """Probe the default jax backend in a SUBPROCESS; if it cannot initialize within
    the timeout (e.g. a wedged TPU tunnel), fall back to CPU in this process so the
    bench always reports a number. The probe must be out-of-process: a hung backend
    init inside this process would hold jax's init lock forever.

    Returns a diagnosis dict recorded in the bench JSON so a failed probe is
    debuggable from the artifact alone (platform seen, stderr tail, per-attempt rc).
    """
    import subprocess

    diag = {"attempts": []}
    for timeout_s in timeouts_s:
        try:
            r = subprocess.run(
                [
                    sys.executable,
                    "-c",
                    "import jax; d=jax.devices(); print(d[0].platform, len(d))",
                ],
                timeout=timeout_s,
                capture_output=True,
                text=True,
            )
            diag["attempts"].append(
                {
                    "rc": r.returncode,
                    "stdout": r.stdout.strip()[-200:],
                    "stderr": r.stderr.strip()[-500:],
                }
            )
            if r.returncode == 0:
                diag["probe"] = "ok"
                diag["platform"] = r.stdout.split()[0] if r.stdout.split() else "?"
                return diag
        except subprocess.TimeoutExpired as e:
            diag["attempts"].append(
                {
                    "rc": "timeout",
                    "timeout_s": timeout_s,
                    "stderr": ((e.stderr or b"").decode(errors="replace")).strip()[-500:],
                }
            )
    import jax

    jax.config.update("jax_platforms", "cpu")
    diag["probe"] = "failed; benching on cpu"
    print(json.dumps({"warning": diag["probe"], "diag": diag}), file=sys.stderr)
    return diag


def main():
    t_setup0 = time.time()
    if os.environ.get("BENCH_FORCE_CPU"):
        # Local-iteration escape hatch: skip the slow tunnel probe entirely.
        import jax

        jax.config.update("jax_platforms", "cpu")
        backend_diag = {"probe": "skipped (BENCH_FORCE_CPU)"}
    else:
        backend_diag = _ensure_live_backend()
    from hyperspace_tpu import IndexConfig, IndexConstants
    from hyperspace_tpu.engine import HyperspaceSession, col
    from hyperspace_tpu.hyperspace import Hyperspace, disable_hyperspace, enable_hyperspace

    n_lineitem = int(os.environ.get("BENCH_LINEITEM_ROWS", 2_000_000))
    n_orders = int(os.environ.get("BENCH_ORDERS_ROWS", 250_000))
    num_buckets = int(os.environ.get("BENCH_NUM_BUCKETS", 64))
    runs = int(os.environ.get("BENCH_RUNS", 5))

    base = tempfile.mkdtemp(prefix="hs_bench_")
    try:
        s = HyperspaceSession(warehouse=base)
        s.conf.set(IndexConstants.INDEX_SYSTEM_PATH, os.path.join(base, "indexes"))
        s.conf.set(IndexConstants.INDEX_NUM_BUCKETS, num_buckets)

        rng = np.random.RandomState(42)
        s.write_parquet(
            {
                "orderkey": rng.randint(0, n_orders, n_lineitem).astype(np.int64),
                "qty": rng.randint(1, 51, n_lineitem).astype(np.int64),
            },
            os.path.join(base, "lineitem"),
        )
        s.write_parquet(
            {
                "o_orderkey": np.arange(n_orders, dtype=np.int64),
                "o_custkey": rng.randint(0, 10_000, n_orders).astype(np.int64),
            },
            os.path.join(base, "orders"),
        )

        def query():
            l = s.read.parquet(os.path.join(base, "lineitem"))
            o = s.read.parquet(os.path.join(base, "orders"))
            return l.join(o, col("orderkey") == col("o_orderkey")).select("qty", "o_custkey")

        def timed_p50(fn, n):
            times = []
            for _ in range(n):
                t0 = time.time()
                fn()
                times.append(time.time() - t0)
            return float(np.percentile(times, 50))

        # Baseline: non-indexed sort-merge join (same engine, same hardware).
        disable_hyperspace(s)
        query().count()  # warm-up compile
        scan_p50 = timed_p50(lambda: query().count(), runs)

        # Indexed path: build both covering indexes, then the bucketed join.
        hs = Hyperspace(s)
        t0 = time.time()
        hs.create_index(
            s.read.parquet(os.path.join(base, "lineitem")),
            IndexConfig("liIdx", ["orderkey"], ["qty"]),
        )
        hs.create_index(
            s.read.parquet(os.path.join(base, "orders")),
            IndexConfig("ordIdx", ["o_orderkey"], ["o_custkey"]),
        )
        build_s = time.time() - t0

        enable_hyperspace(s)
        t0 = time.time()
        rows_indexed = query().count()  # warm-up compile + correctness probe
        indexed_cold_s = time.time() - t0  # io-dominated: decode + upload + compile
        disable_hyperspace(s)
        rows_scan = query().count()
        assert rows_indexed == rows_scan, (rows_indexed, rows_scan)
        enable_hyperspace(s)
        indexed_p50 = timed_p50(lambda: query().count(), runs)

        value = build_s + indexed_p50
        speedup = scan_p50 / indexed_p50 if indexed_p50 > 0 else float("inf")
        print(
            json.dumps(
                {
                    "metric": (
                        f"tpch-small({n_lineitem}x{n_orders}) covering-index "
                        "build+indexed-join-p50 wall-clock"
                    ),
                    "value": round(value, 3),
                    "unit": "s",
                    "vs_baseline": round(speedup, 3),
                    "detail": {
                        "build_s": round(build_s, 3),
                        "indexed_join_p50_s": round(indexed_p50, 3),
                        # First indexed query pays file decode + device upload +
                        # compile; steady-state p50 is device/probe work. The gap
                        # is the io component.
                        "indexed_cold_s": round(indexed_cold_s, 3),
                        "io_s": round(max(0.0, indexed_cold_s - indexed_p50), 3),
                        "scan_join_p50_s": round(scan_p50, 3),
                        "rows": rows_indexed,
                        "backend": __import__("jax").devices()[0].platform,
                        "backend_probe": backend_diag,
                        "setup_s": round(time.time() - t_setup0, 1),
                    },
                }
            )
        )
    finally:
        shutil.rmtree(base, ignore_errors=True)


if __name__ == "__main__":
    main()
