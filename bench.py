"""Benchmark: covering-index build + indexed join query vs the non-indexed scan path.

Runs the BASELINE.md config-2 shape (two CoveringIndexes on TPC-H-style
lineitem/orders; bucketed sort-merge join) at a size that fits one chip, plus a
grouped-aggregation variant (TPC-H Q3-like: groupby-sum over the indexed join).

Prints ONE JSON line:
  metric       what was measured
  value        indexed path wall-clock: index build (both sides) + indexed-join p50
  unit         "s"
  vs_baseline  speedup of the indexed join p50 over the non-indexed sort-merge
               join p50 on identical hardware (the reference's own headline
               mechanism: shuffle elimination; north star is 5x)
  detail       io/device breakdown, device_time_s + utilization (roofline),
               aggregate-query timings, backend + probe diagnostics

Process model: the TPU terminal behind the axon tunnel grants ONE claim per
process, and a killed client can leave the claim wedged (observed: TCP ESTAB to
the relay, terminal never answers — r1/r2 both timed out here). So the WHOLE
bench runs inside a single child process that initializes the backend once; the
parent only supervises with a long timeout, collects a faulthandler stack dump
on hang (SIGABRT before SIGKILL → the artifact names the layer that froze),
and falls back to an in-process CPU run so a number is always reported.
"""

import faulthandler
import json
import os
import shutil
import signal
import subprocess
import sys
import tempfile
import time

import numpy as np

_CHILD_ENV = "BENCH_CHILD"
_CHILD_TIMEOUT_S = int(os.environ.get("BENCH_TPU_TIMEOUT_S", 600))

# v5e (TPU v5 lite) single-chip peaks, for the roofline denominator.
# HBM 16 GiB @ ~819 GB/s; bf16 peak ~197 TFLOP/s. The index workloads are
# sort/probe/gather — bandwidth-bound — so utilization is reported against
# HBM peak. CPU fallback uses a nominal 50 GB/s so the field stays comparable.
_PEAK_BW = {"tpu": 819e9, "cpu": 50e9}


def _now():
    return time.time()


def timed_p50(fn, n: int) -> float:
    times = []
    for _ in range(n):
        t0 = _now()
        fn()
        times.append(_now() - t0)
    return float(np.percentile(times, 50))


def run_bench() -> dict:
    from hyperspace_tpu import IndexConfig, IndexConstants
    from hyperspace_tpu.engine import HyperspaceSession, col
    from hyperspace_tpu.hyperspace import Hyperspace, disable_hyperspace, enable_hyperspace

    import jax

    n_lineitem = int(os.environ.get("BENCH_LINEITEM_ROWS", 2_000_000))
    n_orders = int(os.environ.get("BENCH_ORDERS_ROWS", 250_000))
    num_buckets = int(os.environ.get("BENCH_NUM_BUCKETS", 64))
    runs = int(os.environ.get("BENCH_RUNS", 5))

    backend = jax.devices()[0].platform
    base = tempfile.mkdtemp(prefix="hs_bench_")
    try:
        s = HyperspaceSession(warehouse=base)
        s.conf.set(IndexConstants.INDEX_SYSTEM_PATH, os.path.join(base, "indexes"))
        s.conf.set(IndexConstants.INDEX_NUM_BUCKETS, num_buckets)

        rng = np.random.RandomState(42)
        s.write_parquet(
            {
                "orderkey": rng.randint(0, n_orders, n_lineitem).astype(np.int64),
                "qty": rng.randint(1, 51, n_lineitem).astype(np.int64),
                "price": (rng.rand(n_lineitem) * 1000).astype(np.float64),
                "discount": (rng.randint(0, 11, n_lineitem) / 100.0).astype(np.float64),
            },
            os.path.join(base, "lineitem"),
        )
        s.write_parquet(
            {
                "o_orderkey": np.arange(n_orders, dtype=np.int64),
                "o_custkey": rng.randint(0, 10_000, n_orders).astype(np.int64),
            },
            os.path.join(base, "orders"),
        )

        def query():
            l = s.read.parquet(os.path.join(base, "lineitem"))
            o = s.read.parquet(os.path.join(base, "orders"))
            return l.join(o, col("orderkey") == col("o_orderkey")).select("qty", "o_custkey")

        def agg_query():
            # TPC-H Q3 shape: SUM(price * (1 - discount)) revenue grouped over
            # the indexed join.
            l = s.read.parquet(os.path.join(base, "lineitem"))
            o = s.read.parquet(os.path.join(base, "orders"))
            return (
                l.join(o, col("orderkey") == col("o_orderkey"))
                .with_column("revenue", col("price") * (1 - col("discount")))
                .group_by("o_custkey")
                .agg(revenue=("revenue", "sum"), n=("qty", "count"))
                .order_by(("revenue", False))
                .limit(10)
            )

        # Baseline: non-indexed sort-merge join (same engine, same hardware).
        disable_hyperspace(s)
        query().count()  # warm-up compile
        scan_p50 = timed_p50(lambda: query().count(), runs)
        agg_query().count()
        agg_scan_p50 = timed_p50(lambda: agg_query().count(), runs)

        # Indexed path: build both covering indexes, then the bucketed join.
        hs = Hyperspace(s)
        t0 = _now()
        hs.create_index(
            s.read.parquet(os.path.join(base, "lineitem")),
            IndexConfig("liIdx", ["orderkey"], ["qty", "price", "discount"]),
        )
        hs.create_index(
            s.read.parquet(os.path.join(base, "orders")),
            IndexConfig("ordIdx", ["o_orderkey"], ["o_custkey"]),
        )
        build_s = _now() - t0

        enable_hyperspace(s)
        t0 = _now()
        rows_indexed = query().count()  # warm-up compile + correctness probe
        indexed_cold_s = _now() - t0  # io-dominated: decode + upload + compile
        disable_hyperspace(s)
        rows_scan = query().count()
        assert rows_indexed == rows_scan, (rows_indexed, rows_scan)
        enable_hyperspace(s)
        indexed_p50 = timed_p50(lambda: query().count(), runs)
        agg_query().count()
        agg_indexed_p50 = timed_p50(lambda: agg_query().count(), runs)

        # --- Workload variants (r2 review: "single bench shape") -------------
        variants = _variant_section(s, base, col, runs, hs)

        # --- Device-time / roofline: time the core probe kernel on-device. ---
        # The steady-state indexed join = cached padded reps -> probe -> host
        # expand+gather. Re-run just the probe with block_until_ready deltas to
        # split device kernel time out of the end-to-end p50, and model bytes
        # touched (pad+sort reads/writes + probe reads over both padded
        # matrices) for an achieved-bandwidth roofline.
        device = _device_section(s, base, col, runs, backend)

        value = build_s + indexed_p50
        speedup = scan_p50 / indexed_p50 if indexed_p50 > 0 else float("inf")
        return {
            "metric": (
                f"tpch-small({n_lineitem}x{n_orders}) covering-index "
                "build+indexed-join-p50 wall-clock"
            ),
            "value": round(value, 3),
            "unit": "s",
            "vs_baseline": round(speedup, 3),
            "detail": {
                "build_s": round(build_s, 3),
                "indexed_join_p50_s": round(indexed_p50, 3),
                # First indexed query pays file decode + device upload +
                # compile; steady-state p50 is device/probe work. The gap
                # is the io component.
                "indexed_cold_s": round(indexed_cold_s, 3),
                "io_s": round(max(0.0, indexed_cold_s - indexed_p50), 3),
                "scan_join_p50_s": round(scan_p50, 3),
                "agg_scan_p50_s": round(agg_scan_p50, 3),
                "agg_indexed_p50_s": round(agg_indexed_p50, 3),
                "agg_speedup": round(
                    agg_scan_p50 / agg_indexed_p50 if agg_indexed_p50 > 0 else float("inf"), 3
                ),
                "rows": rows_indexed,
                "backend": backend,
                "variants": variants,
                **device,
            },
        }
    finally:
        shutil.rmtree(base, ignore_errors=True)


def _variant_section(s, base, col, runs, hs) -> dict:
    """Beyond the headline int-key join: string-key join, filter-index point
    lookup, and data-skipping file pruning — each with its non-indexed
    counterpart on the same engine/hardware (r2 weak item 7: the extension
    features had correctness tests but zero performance characterization)."""
    from hyperspace_tpu import IndexConfig
    from hyperspace_tpu.hyperspace import disable_hyperspace, enable_hyperspace
    from hyperspace_tpu.index.dataskipping import DataSkippingIndexConfig, MinMaxSketch

    n = int(os.environ.get("BENCH_VARIANT_ROWS", 500_000))
    rng = np.random.RandomState(9)

    def p50(fn):
        return round(timed_p50(fn, runs), 4)

    out = {}
    # String-key join: dictionary-encoded keys ride the same hashed probe.
    s.write_parquet(
        {
            "sku": np.array([f"sku-{i % 50_000:06d}" for i in range(n)]),
            "qty": rng.randint(1, 9, n).astype(np.int64),
        },
        os.path.join(base, "li_str"),
    )
    s.write_parquet(
        {
            "sku2": np.array([f"sku-{i:06d}" for i in range(50_000)]),
            "weight": rng.randint(1, 99, 50_000).astype(np.int64),
        },
        os.path.join(base, "dim_str"),
    )
    hs.create_index(
        s.read.parquet(os.path.join(base, "li_str")),
        IndexConfig("vLiStr", ["sku"], ["qty"]),
    )
    hs.create_index(
        s.read.parquet(os.path.join(base, "dim_str")),
        IndexConfig("vDimStr", ["sku2"], ["weight"]),
    )

    def qs():
        l = s.read.parquet(os.path.join(base, "li_str"))
        d = s.read.parquet(os.path.join(base, "dim_str"))
        return l.join(d, col("sku") == col("sku2")).select("qty", "weight")

    disable_hyperspace(s)
    qs().count()
    out["string_join_scan_p50_s"] = p50(lambda: qs().count())
    enable_hyperspace(s)
    qs().count()
    out["string_join_indexed_p50_s"] = p50(lambda: qs().count())

    # Filter-index point lookup (BASELINE config-1 shape).
    def qf():
        return (
            s.read.parquet(os.path.join(base, "dim_str"))
            .filter(col("sku2") == "sku-012345")
            .select("weight")
        )

    disable_hyperspace(s)
    qf().collect()
    out["filter_scan_p50_s"] = p50(lambda: qf().collect())
    enable_hyperspace(s)
    qf().collect()
    out["filter_indexed_p50_s"] = p50(lambda: qf().collect())

    # Data skipping: 16 range-partitioned files, MinMax sketch prunes 15.
    ds_dir = os.path.join(base, "events_ds")
    per = n // 16
    for i in range(16):
        t = {
            "ts": (np.arange(per, dtype=np.int64) + i * per),
            "val": rng.randint(0, 1000, per).astype(np.int64),
        }
        from hyperspace_tpu.engine import io as _eio
        from hyperspace_tpu.engine.table import Table as _T

        _eio.write_parquet(_T.from_pydict(t), os.path.join(ds_dir, f"part-{i:05d}.parquet"))
    hs.create_index(
        s.read.parquet(ds_dir), DataSkippingIndexConfig("vDs", [MinMaxSketch("ts")])
    )
    probe_ts = 3 * per + 7

    def qd():
        return (
            s.read.parquet(ds_dir).filter(col("ts") == probe_ts).select("val")
        )

    disable_hyperspace(s)
    qd().collect()
    out["dataskip_scan_p50_s"] = p50(lambda: qd().collect())
    enable_hyperspace(s)
    qd().collect()
    out["dataskip_indexed_p50_s"] = p50(lambda: qd().collect())
    plan = qd().explain_string()
    import re as _re

    m = _re.search(r"pruned by", plan)
    out["dataskip_pruning_active"] = bool(m)
    return out


def _device_section(s, base, col, runs, backend) -> dict:
    """Isolate the on-device probe kernel from the end-to-end query: build the
    cached padded reps once, then time probe dispatch→block_until_ready. Bytes
    model (documented lower bound): the pad+sort pass reads+writes each padded
    key matrix once and the binary-search probe reads both again →
    3*(|L|+|R|) int64 traffic."""
    import jax

    from hyperspace_tpu.engine import physical as phys

    l = s.read.parquet(os.path.join(base, "lineitem"))
    o = s.read.parquet(os.path.join(base, "orders"))
    df = l.join(o, col("orderkey") == col("o_orderkey")).select("qty", "o_custkey")
    plan = df.physical_plan()
    join_exec = None
    stack = [plan]
    while stack:
        node = stack.pop()
        if isinstance(node, phys.SortMergeJoinExec) and node.bucketed:
            join_exec = node
            break
        stack.extend(node.children())
    if join_exec is None:
        return {
            "device_time_s": None,
            "utilization": None,
            "device_note": "no bucketed join in plan",
        }

    from hyperspace_tpu.engine.physical import ExecContext, _padded_rep
    from hyperspace_tpu.ops.bucket_join import _probe

    ctx = ExecContext(session=s)
    left, l_starts = join_exec.left.execute_concat(ctx)
    right, r_starts = join_exec.right.execute_concat(ctx)
    # Same rep + mode reconciliation as SortMergeJoinExec._execute_bucketed, so the
    # timed kernel is EXACTLY the one production queries dispatch.
    l_rep = _padded_rep(left, l_starts, join_exec.left_keys)
    r_rep = _padded_rep(right, r_starts, join_exec.right_keys)
    if l_rep.mode != r_rep.mode:
        if l_rep.mode == "value":
            l_rep = _padded_rep(left, l_starts, join_exec.left_keys, force_hash=True)
        else:
            r_rep = _padded_rep(right, r_starts, join_exec.right_keys, force_hash=True)
    # Same orientation + promotion as probe_padded — one shared heuristic, so
    # the timed kernel cannot drift from what production dispatches.
    from hyperspace_tpu.ops.bucket_join import probe_keys_promoted, probe_orientation

    a, b, _ = probe_orientation(l_rep, r_rep)
    lk, rk = probe_keys_promoted(a.keys, b.keys)

    def one():
        jax.block_until_ready(_probe(lk, rk, a.lengths, b.lengths))

    one()  # compile
    from hyperspace_tpu.telemetry.profiling import annotate, trace

    profiling = bool(os.environ.get("BENCH_PROFILE_DIR"))
    times = []
    with trace(os.environ.get("BENCH_PROFILE_DIR")):  # xprof when requested
        for _ in range(runs):
            t0 = _now()
            with annotate("bucketed-probe", enabled=profiling):
                one()
            times.append(_now() - t0)
    device_time_s = float(np.percentile(times, 50))
    nbytes = 3 * lk.dtype.itemsize * (
        int(np.prod(lk.shape)) + int(np.prod(rk.shape))
    )
    peak = _PEAK_BW.get(backend, _PEAK_BW["cpu"])
    achieved = nbytes / device_time_s if device_time_s > 0 else 0.0
    return {
        "device_time_s": round(device_time_s, 5),
        "device_bytes_modeled": nbytes,
        "achieved_gbps": round(achieved / 1e9, 2),
        "peak_gbps": round(peak / 1e9, 1),
        "utilization": round(achieved / peak, 4),
    }


def run_distributed_bench() -> dict:
    """Distributed-mode measurement on the virtual 8-device CPU mesh (multi-chip
    hardware is not reachable from the bench host): mesh build + sharded
    co-bucketed probe + real-exchange general join, with the steady-state block
    instrumentation showing the probe path free of per-query key uploads
    (`DIST_JOIN_STATS`)."""
    from hyperspace_tpu.parallel.mesh import force_virtual_cpu

    n_dev = int(os.environ.get("BENCH_DIST_DEVICES", 8))
    force_virtual_cpu(n_dev)

    from hyperspace_tpu import IndexConfig, IndexConstants
    from hyperspace_tpu.engine import HyperspaceSession, col
    from hyperspace_tpu.hyperspace import Hyperspace, enable_hyperspace
    from hyperspace_tpu.parallel.table_ops import DIST_JOIN_STATS

    n_l = int(os.environ.get("BENCH_DIST_LINEITEM_ROWS", 400_000))
    n_o = int(os.environ.get("BENCH_DIST_ORDERS_ROWS", 50_000))
    runs = int(os.environ.get("BENCH_RUNS", 5))
    base = tempfile.mkdtemp(prefix="hs_dbench_")
    try:
        s = HyperspaceSession(warehouse=base)
        s.conf.set(IndexConstants.INDEX_SYSTEM_PATH, os.path.join(base, "indexes"))
        s.conf.set(IndexConstants.INDEX_NUM_BUCKETS, 64)
        s.conf.set(IndexConstants.DISTRIBUTED_MIN_ROWS, 0)
        rng = np.random.RandomState(7)
        s.write_parquet(
            {
                "orderkey": rng.randint(0, n_o, n_l).astype(np.int64),
                "qty": rng.randint(1, 51, n_l).astype(np.int64),
            },
            os.path.join(base, "lineitem"),
        )
        s.write_parquet(
            {
                "o_orderkey": np.arange(n_o, dtype=np.int64),
                "o_custkey": rng.randint(0, 10_000, n_o).astype(np.int64),
            },
            os.path.join(base, "orders"),
        )

        def query():
            l = s.read.parquet(os.path.join(base, "lineitem"))
            o = s.read.parquet(os.path.join(base, "orders"))
            return l.join(o, col("orderkey") == col("o_orderkey")).select("qty", "o_custkey")

        hs = Hyperspace(s)
        t0 = _now()
        hs.create_index(
            s.read.parquet(os.path.join(base, "lineitem")),
            IndexConfig("dLiIdx", ["orderkey"], ["qty"]),
        )
        hs.create_index(
            s.read.parquet(os.path.join(base, "orders")),
            IndexConfig("dOrdIdx", ["o_orderkey"], ["o_custkey"]),
        )
        dist_build_s = _now() - t0

        enable_hyperspace(s)
        query().count()  # warm-up: block layouts built + compile
        b0, p0 = DIST_JOIN_STATS["block_builds"], DIST_JOIN_STATS["probes"]
        times = []
        for _ in range(runs):
            t0 = _now()
            query().count()
            times.append(_now() - t0)
        steady_builds = DIST_JOIN_STATS["block_builds"] - b0
        steady_probes = DIST_JOIN_STATS["probes"] - p0

        # General join through the REAL exchange (no index): per-query all_to_all.
        from hyperspace_tpu.hyperspace import disable_hyperspace

        disable_hyperspace(s)
        query().count()
        ex_times = []
        for _ in range(runs):
            t0 = _now()
            query().count()
            ex_times.append(_now() - t0)
        return {
            "devices": n_dev,
            "rows": n_l,
            "dist_build_s": round(dist_build_s, 3),
            "dist_indexed_p50_s": round(float(np.percentile(times, 50)), 3),
            "dist_exchange_join_p50_s": round(float(np.percentile(ex_times, 50)), 3),
            # Steady state: probes ran every query, block layouts uploaded zero
            # times after warm-up — the probe path is free of per-query key
            # round-trips (r2 weak item 4/8).
            "steady_block_builds": steady_builds,
            "steady_probes": steady_probes,
        }
    finally:
        shutil.rmtree(base, ignore_errors=True)


def _child_main():
    faulthandler.enable()
    # SIGUSR1 from the supervising parent dumps every thread's stack to stderr
    # before the kill — the hang diagnosis rides the bench artifact.
    faulthandler.register(signal.SIGUSR1, all_threads=True)
    if os.environ.get(_CHILD_ENV) == "dist":
        print(json.dumps(run_distributed_bench()), flush=True)
        return
    # Init handshake: the parent aborts early when the backend claim is wedged
    # (observed failure mode: jax.devices() blocks forever on the terminal
    # claim). A fast line here = init succeeded, the full budget applies.
    import jax

    print(f"BENCH_CHILD_INIT_OK {jax.devices()[0].platform}", flush=True)
    result = run_bench()
    print(json.dumps(result), flush=True)


def _run_distributed_subprocess() -> dict:
    """Run the distributed section in its own process (it needs the virtual CPU
    mesh, which must be set before backend init)."""
    env = dict(os.environ)
    env[_CHILD_ENV] = "dist"
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)  # force_virtual_cpu sets its own
    try:
        r = subprocess.run(
            [sys.executable, os.path.abspath(__file__)],
            env=env,
            capture_output=True,
            text=True,
            timeout=int(os.environ.get("BENCH_DIST_TIMEOUT_S", 300)),
        )
        if r.returncode == 0 and r.stdout.strip():
            return json.loads(r.stdout.strip().splitlines()[-1])
        return {"error": f"rc={r.returncode}", "stderr": r.stderr.strip()[-400:]}
    except subprocess.TimeoutExpired:
        return {"error": "timeout"}
    except (ValueError, KeyError) as e:
        return {"error": f"{type(e).__name__}: {e}"}


def main():
    if os.environ.get(_CHILD_ENV):
        _child_main()
        return
    t_setup0 = _now()
    diag = {"attempts": []}
    if not os.environ.get("BENCH_FORCE_CPU"):
        import threading

        env = dict(os.environ)
        env[_CHILD_ENV] = "1"
        env.setdefault("JAX_PLATFORMS", "axon")
        p = subprocess.Popen(
            [sys.executable, os.path.abspath(__file__)],
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
        )
        out_lines, err_chunks = [], []
        init_ok = threading.Event()

        def _rd_out():
            for line in p.stdout:
                out_lines.append(line)
                if line.startswith("BENCH_CHILD_INIT_OK"):
                    init_ok.set()

        def _rd_err():
            err_chunks.append(p.stderr.read() or "")

        t_out = threading.Thread(target=_rd_out, daemon=True)
        t_err = threading.Thread(target=_rd_err, daemon=True)
        t_out.start()
        t_err.start()

        # Two-stage budget: a wedged terminal claim hangs backend init forever
        # (observed failure mode), so give INIT a short deadline; once init
        # reports, the full budget covers compile + the bench itself.
        init_timeout = int(os.environ.get("BENCH_TPU_INIT_TIMEOUT_S", 150))
        deadline = _now() + init_timeout
        while not init_ok.is_set() and p.poll() is None and _now() < deadline:
            init_ok.wait(timeout=1)  # also returns promptly on child exit
        timed_out = False
        if not init_ok.is_set() and p.poll() is None:
            timed_out = True
            stage = f"init-timeout ({init_timeout}s)"
        else:
            try:
                p.wait(timeout=_CHILD_TIMEOUT_S)
            except subprocess.TimeoutExpired:
                timed_out = True
                stage = f"run-timeout ({_CHILD_TIMEOUT_S}s)"
        if timed_out:
            # Stack-dump then kill: SIGUSR1 triggers the child's faulthandler,
            # so the artifact records WHERE init/compute froze (e.g. stuck in
            # PJRT_Client_Create waiting on the terminal claim).
            p.send_signal(signal.SIGUSR1)
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait()
        t_out.join(timeout=5)
        t_err.join(timeout=5)
        err = "".join(err_chunks)
        out = "".join(out_lines)
        if timed_out:
            diag["attempts"].append(
                {"rc": stage, "stderr_stack_tail": err.strip()[-1500:]}
            )
        else:
            diag["attempts"].append({"rc": p.returncode, "stderr": err.strip()[-800:]})
            if p.returncode == 0 and out.strip():
                try:
                    result = json.loads(out.strip().splitlines()[-1])
                    _finish(result, {"probe": "ok (single-claim child)"}, t_setup0)
                    return
                except (ValueError, KeyError, IndexError) as e:
                    # Malformed child stdout (interleaved banners etc.): record
                    # and fall through to the CPU run — a number is always printed.
                    diag["attempts"][-1]["parse_error"] = f"{type(e).__name__}: {e}"
        diag["probe"] = "tpu child failed; benching on cpu"
        print(json.dumps({"warning": diag["probe"]}), file=sys.stderr)
    else:
        diag = {"probe": "skipped (BENCH_FORCE_CPU)"}

    import jax

    jax.config.update("jax_platforms", "cpu")
    result = run_bench()
    _finish(result, diag, t_setup0)


def _finish(result: dict, diag: dict, t_setup0: float) -> None:
    if not os.environ.get("BENCH_SKIP_DIST"):
        # Distributed-mode section (virtual mesh, own process): mesh build +
        # sharded probe + exchange join with steady-state instrumentation.
        result["detail"]["distributed"] = _run_distributed_subprocess()
    result["detail"]["backend_probe"] = diag
    result["detail"]["setup_s"] = round(_now() - t_setup0, 1)
    print(json.dumps(result))


if __name__ == "__main__":
    main()
