"""Benchmark: covering-index build + indexed join query vs the non-indexed scan path.

Runs the BASELINE.md config-2 shape (two CoveringIndexes on TPC-H-style
lineitem/orders; bucketed sort-merge join) at a size that fits one chip, plus a
grouped-aggregation variant (TPC-H Q3-like: groupby-sum over the indexed join).

Prints ONE JSON line:
  metric       what was measured
  value        indexed path wall-clock: index build (both sides) + indexed-join p50
  unit         "s"
  vs_baseline  speedup of the indexed join p50 over the non-indexed sort-merge
               join p50 on identical hardware (the reference's own headline
               mechanism: shuffle elimination; north star is 5x)
  detail       io/device breakdown, device_time_s + utilization (roofline),
               aggregate-query timings, backend + probe diagnostics

Process model: the TPU terminal behind the axon tunnel grants ONE claim per
process, and a killed client can leave the claim wedged (observed: TCP ESTAB to
the relay, terminal never answers — r1/r2 both timed out here). So the WHOLE
bench runs inside a single child process that initializes the backend once; the
parent only supervises with a long timeout, collects a faulthandler stack dump
on hang (SIGABRT before SIGKILL → the artifact names the layer that froze),
and falls back to an in-process CPU run so a number is always reported.
"""

import faulthandler
import json
import os
import shutil
import signal
import subprocess
import sys
import tempfile
import time

import numpy as np

_CHILD_ENV = "BENCH_CHILD"
_CHILD_TIMEOUT_S = int(os.environ.get("BENCH_TPU_TIMEOUT_S", 600))

# v5e (TPU v5 lite) single-chip peaks, for the roofline denominator.
# HBM 16 GiB @ ~819 GB/s; bf16 peak ~197 TFLOP/s. The index workloads are
# sort/probe/gather — bandwidth-bound — so utilization is reported against
# HBM peak. CPU fallback uses a nominal 50 GB/s so the field stays comparable.
_PEAK_BW = {"tpu": 819e9, "cpu": 50e9}


def _now():
    return time.time()


def run_bench() -> dict:
    from hyperspace_tpu import IndexConfig, IndexConstants
    from hyperspace_tpu.engine import HyperspaceSession, col
    from hyperspace_tpu.hyperspace import Hyperspace, disable_hyperspace, enable_hyperspace

    import jax

    n_lineitem = int(os.environ.get("BENCH_LINEITEM_ROWS", 2_000_000))
    n_orders = int(os.environ.get("BENCH_ORDERS_ROWS", 250_000))
    num_buckets = int(os.environ.get("BENCH_NUM_BUCKETS", 64))
    runs = int(os.environ.get("BENCH_RUNS", 5))

    backend = jax.devices()[0].platform
    base = tempfile.mkdtemp(prefix="hs_bench_")
    try:
        s = HyperspaceSession(warehouse=base)
        s.conf.set(IndexConstants.INDEX_SYSTEM_PATH, os.path.join(base, "indexes"))
        s.conf.set(IndexConstants.INDEX_NUM_BUCKETS, num_buckets)

        rng = np.random.RandomState(42)
        s.write_parquet(
            {
                "orderkey": rng.randint(0, n_orders, n_lineitem).astype(np.int64),
                "qty": rng.randint(1, 51, n_lineitem).astype(np.int64),
            },
            os.path.join(base, "lineitem"),
        )
        s.write_parquet(
            {
                "o_orderkey": np.arange(n_orders, dtype=np.int64),
                "o_custkey": rng.randint(0, 10_000, n_orders).astype(np.int64),
            },
            os.path.join(base, "orders"),
        )

        def query():
            l = s.read.parquet(os.path.join(base, "lineitem"))
            o = s.read.parquet(os.path.join(base, "orders"))
            return l.join(o, col("orderkey") == col("o_orderkey")).select("qty", "o_custkey")

        def agg_query():
            # TPC-H Q3-like: grouped aggregation over the indexed join.
            l = s.read.parquet(os.path.join(base, "lineitem"))
            o = s.read.parquet(os.path.join(base, "orders"))
            return (
                l.join(o, col("orderkey") == col("o_orderkey"))
                .group_by("o_custkey")
                .agg(sum_qty=("qty", "sum"), n=("qty", "count"))
            )

        def timed_p50(fn, n):
            times = []
            for _ in range(n):
                t0 = _now()
                fn()
                times.append(_now() - t0)
            return float(np.percentile(times, 50))

        # Baseline: non-indexed sort-merge join (same engine, same hardware).
        disable_hyperspace(s)
        query().count()  # warm-up compile
        scan_p50 = timed_p50(lambda: query().count(), runs)
        agg_query().count()
        agg_scan_p50 = timed_p50(lambda: agg_query().count(), runs)

        # Indexed path: build both covering indexes, then the bucketed join.
        hs = Hyperspace(s)
        t0 = _now()
        hs.create_index(
            s.read.parquet(os.path.join(base, "lineitem")),
            IndexConfig("liIdx", ["orderkey"], ["qty"]),
        )
        hs.create_index(
            s.read.parquet(os.path.join(base, "orders")),
            IndexConfig("ordIdx", ["o_orderkey"], ["o_custkey"]),
        )
        build_s = _now() - t0

        enable_hyperspace(s)
        t0 = _now()
        rows_indexed = query().count()  # warm-up compile + correctness probe
        indexed_cold_s = _now() - t0  # io-dominated: decode + upload + compile
        disable_hyperspace(s)
        rows_scan = query().count()
        assert rows_indexed == rows_scan, (rows_indexed, rows_scan)
        enable_hyperspace(s)
        indexed_p50 = timed_p50(lambda: query().count(), runs)
        agg_query().count()
        agg_indexed_p50 = timed_p50(lambda: agg_query().count(), runs)

        # --- Device-time / roofline: time the core probe kernel on-device. ---
        # The steady-state indexed join = cached padded reps -> probe -> host
        # expand+gather. Re-run just the probe with block_until_ready deltas to
        # split device kernel time out of the end-to-end p50, and model bytes
        # touched (pad+sort reads/writes + probe reads over both padded
        # matrices) for an achieved-bandwidth roofline.
        device = _device_section(s, base, col, runs, backend)

        value = build_s + indexed_p50
        speedup = scan_p50 / indexed_p50 if indexed_p50 > 0 else float("inf")
        return {
            "metric": (
                f"tpch-small({n_lineitem}x{n_orders}) covering-index "
                "build+indexed-join-p50 wall-clock"
            ),
            "value": round(value, 3),
            "unit": "s",
            "vs_baseline": round(speedup, 3),
            "detail": {
                "build_s": round(build_s, 3),
                "indexed_join_p50_s": round(indexed_p50, 3),
                # First indexed query pays file decode + device upload +
                # compile; steady-state p50 is device/probe work. The gap
                # is the io component.
                "indexed_cold_s": round(indexed_cold_s, 3),
                "io_s": round(max(0.0, indexed_cold_s - indexed_p50), 3),
                "scan_join_p50_s": round(scan_p50, 3),
                "agg_scan_p50_s": round(agg_scan_p50, 3),
                "agg_indexed_p50_s": round(agg_indexed_p50, 3),
                "agg_speedup": round(
                    agg_scan_p50 / agg_indexed_p50 if agg_indexed_p50 > 0 else float("inf"), 3
                ),
                "rows": rows_indexed,
                "backend": backend,
                **device,
            },
        }
    finally:
        shutil.rmtree(base, ignore_errors=True)


def _device_section(s, base, col, runs, backend) -> dict:
    """Isolate the on-device probe kernel from the end-to-end query: build the
    cached padded reps once, then time probe dispatch→block_until_ready. Bytes
    model (documented lower bound): the pad+sort pass reads+writes each padded
    key matrix once and the binary-search probe reads both again →
    3*(|L|+|R|) int64 traffic."""
    import jax

    from hyperspace_tpu.engine import physical as phys

    l = s.read.parquet(os.path.join(base, "lineitem"))
    o = s.read.parquet(os.path.join(base, "orders"))
    df = l.join(o, col("orderkey") == col("o_orderkey")).select("qty", "o_custkey")
    plan = df.physical_plan()
    join_exec = None
    stack = [plan]
    while stack:
        node = stack.pop()
        if isinstance(node, phys.SortMergeJoinExec) and node.bucketed:
            join_exec = node
            break
        stack.extend(node.children())
    if join_exec is None:
        return {
            "device_time_s": None,
            "utilization": None,
            "device_note": "no bucketed join in plan",
        }

    from hyperspace_tpu.engine.physical import ExecContext, _padded_rep
    from hyperspace_tpu.ops.bucket_join import _probe

    ctx = ExecContext(session=s)
    left, l_starts = join_exec.left.execute_concat(ctx)
    right, r_starts = join_exec.right.execute_concat(ctx)
    # Same rep + mode reconciliation as SortMergeJoinExec._execute_bucketed, so the
    # timed kernel is EXACTLY the one production queries dispatch.
    l_rep = _padded_rep(left, l_starts, join_exec.left_keys)
    r_rep = _padded_rep(right, r_starts, join_exec.right_keys)
    if l_rep.mode != r_rep.mode:
        if l_rep.mode == "value":
            l_rep = _padded_rep(left, l_starts, join_exec.left_keys, force_hash=True)
        else:
            r_rep = _padded_rep(right, r_starts, join_exec.right_keys, force_hash=True)
    lk, rk = l_rep.keys, r_rep.keys
    if lk.dtype != rk.dtype:  # probe_padded's own promotion step
        import jax.numpy as jnp

        common = jnp.promote_types(lk.dtype, rk.dtype)
        lk, rk = lk.astype(common), rk.astype(common)

    def one():
        jax.block_until_ready(_probe(lk, rk, l_rep.lengths, r_rep.lengths))

    one()  # compile
    times = []
    for _ in range(runs):
        t0 = _now()
        one()
        times.append(_now() - t0)
    device_time_s = float(np.percentile(times, 50))
    nbytes = 3 * lk.dtype.itemsize * (
        int(np.prod(lk.shape)) + int(np.prod(rk.shape))
    )
    peak = _PEAK_BW.get(backend, _PEAK_BW["cpu"])
    achieved = nbytes / device_time_s if device_time_s > 0 else 0.0
    return {
        "device_time_s": round(device_time_s, 5),
        "device_bytes_modeled": nbytes,
        "achieved_gbps": round(achieved / 1e9, 2),
        "peak_gbps": round(peak / 1e9, 1),
        "utilization": round(achieved / peak, 4),
    }


def _child_main():
    faulthandler.enable()
    # SIGUSR1 from the supervising parent dumps every thread's stack to stderr
    # before the kill — the hang diagnosis rides the bench artifact.
    faulthandler.register(signal.SIGUSR1, all_threads=True)
    result = run_bench()
    print(json.dumps(result), flush=True)


def main():
    if os.environ.get(_CHILD_ENV):
        _child_main()
        return
    t_setup0 = _now()
    diag = {"attempts": []}
    if not os.environ.get("BENCH_FORCE_CPU"):
        env = dict(os.environ)
        env[_CHILD_ENV] = "1"
        env.setdefault("JAX_PLATFORMS", "axon")
        p = subprocess.Popen(
            [sys.executable, os.path.abspath(__file__)],
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
        )
        try:
            out, err = p.communicate(timeout=_CHILD_TIMEOUT_S)
            diag["attempts"].append({"rc": p.returncode, "stderr": err.strip()[-800:]})
            if p.returncode == 0 and out.strip():
                try:
                    result = json.loads(out.strip().splitlines()[-1])
                    result["detail"]["backend_probe"] = {"probe": "ok (single-claim child)"}
                    result["detail"]["setup_s"] = round(_now() - t_setup0, 1)
                    print(json.dumps(result))
                    return
                except (ValueError, KeyError, IndexError) as e:
                    # Malformed child stdout (interleaved banners etc.): record
                    # and fall through to the CPU run — a number is always printed.
                    diag["attempts"][-1]["parse_error"] = f"{type(e).__name__}: {e}"
        except subprocess.TimeoutExpired:
            # Stack-dump then kill: SIGUSR1 triggers the child's faulthandler,
            # so the artifact records WHERE init/compute froze (e.g. stuck in
            # PJRT_Client_Create waiting on the terminal claim).
            p.send_signal(signal.SIGUSR1)
            try:
                out, err = p.communicate(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
                out, err = p.communicate()
            diag["attempts"].append(
                {
                    "rc": "timeout",
                    "timeout_s": _CHILD_TIMEOUT_S,
                    "stderr_stack_tail": (err or "").strip()[-1500:],
                }
            )
        diag["probe"] = "tpu child failed; benching on cpu"
        print(json.dumps({"warning": diag["probe"]}), file=sys.stderr)
    else:
        diag = {"probe": "skipped (BENCH_FORCE_CPU)"}

    import jax

    jax.config.update("jax_platforms", "cpu")
    result = run_bench()
    result["detail"]["backend_probe"] = diag
    result["detail"]["setup_s"] = round(_now() - t_setup0, 1)
    print(json.dumps(result))


if __name__ == "__main__":
    main()
