"""Benchmark: covering-index build + indexed query wall-clock vs the non-indexed
scan path, at TPC-H-shaped scale.

Workload (BASELINE.md config 2 + a Q14-shaped second query):
  - lineitem (default 20M rows on the TPU child / 8M on the CPU fallback;
    7 columns, 16 parquet files), orders (lineitem/8 rows, 4 files),
    part (lineitem/20 rows incl. a dictionary string column, 2 files).
  - Q3 shape: lineitem⋈orders revenue aggregation (groupby-sum, top-10).
  - Q14 shape: shipdate range filter + lineitem⋈part + groupby(p_type) agg.
  Both run non-indexed (sort-merge over the raw scans) and indexed (covering
  indexes both sides → co-bucketed shuffle-free join), same engine, same chip.

Output contract (r3 verdict items 1-2): the LAST stdout line is a compact
(≤200-byte) JSON record {"metric","value","unit","vs_baseline","detail":
{"backend","rows",...}}; the FULL detail rides the second-to-last line as
{"bench_detail": {...}}. The driver's tail-parse therefore always gets a
machine-readable metric even when the detail is large.

Process model (r3 TPU_EVIDENCE.md): the axon TPU terminal grants one claim per
client process and a client killed mid-claim wedges the terminal for the rest
of the session, so the WHOLE bench runs inside a single child process that is
the session's first backend touch; the parent only supervises. The child
prints a `BENCH_PARTIAL <json>` line after every completed phase, so on a
run-timeout the parent still reports the last completed TPU-backed phase
instead of falling back blind.
"""

import faulthandler
import json
import os
import shutil
import signal
import subprocess
import sys
import tempfile
import threading
import time

import numpy as np

_CHILD_ENV = "BENCH_CHILD"
# Budget calibrated on the round-4 mid-round TPU run (TPU_BENCH_r04_validation
# .json): 2M rows end-to-end took ~940 s through the relay — remote-compile
# round-trips dominate, so the budget must cover the fixed compile cost plus
# data-proportional work at the default 8M scale.
_CHILD_TIMEOUT_S = int(os.environ.get("BENCH_TPU_TIMEOUT_S", 2400))

# Persistent XLA compilation cache shared across bench processes: remote-compile
# round-trips dominated the round-4 TPU build (143.9 s wall vs ~7 s device), and
# program shapes are pow2-quantized, so a warm cache from ANY earlier run at the
# same scale (e.g. a mid-round rehearsal) erases most of that tax for the
# driver's end-of-round run. Harmless where the backend can't serialize
# executables (jax logs and proceeds). ONE implementation: the bench defaults
# the engine's documented HYPERSPACE_COMPILE_CACHE_DIR knob (user-supplied
# values, incl. a raw JAX_COMPILATION_CACHE_DIR, win) and the session hook in
# hyperspace_tpu.engine.session applies it.
_COMPILE_CACHE_DIR = (
    os.environ.get("BENCH_COMPILE_CACHE_DIR")
    or os.environ.get("JAX_COMPILATION_CACHE_DIR")
    or os.path.join(os.path.dirname(os.path.abspath(__file__)), ".jax_cache")
)


def _enable_compile_cache() -> None:
    os.environ.setdefault("HYPERSPACE_COMPILE_CACHE_DIR", _COMPILE_CACHE_DIR)
    from hyperspace_tpu.engine.session import _enable_compile_cache_once

    _enable_compile_cache_once()

# v5e (TPU v5 lite) single-chip HBM peak for the roofline denominator; CPU uses
# a nominal 50 GB/s so the field stays comparable across backends.
_PEAK_BW = {"tpu": 819e9, "cpu": 50e9}

_PARTIAL_TAG = "BENCH_PARTIAL "


def _now():
    return time.time()


def _tree_bytes(*roots) -> int:
    """Total on-disk bytes under the given directory trees — the ONE walk
    behind every source-size / GB/s denominator in this file."""
    return sum(
        os.path.getsize(os.path.join(r, f))
        for root in roots
        for r, _ds, fs in os.walk(root)
        for f in fs
    )


def timed_p50(fn, n: int) -> float:
    times = []
    for _ in range(n):
        t0 = _now()
        fn()
        times.append(_now() - t0)
    return float(np.percentile(times, 50))


def _sizes(backend: str):
    """Row counts: 8M default on both backends. Measured reality (round-4
    validation run): the TPU is reachable only through a loopback relay whose
    per-dispatch and remote-compile round-trips dominate wall-clock (2M rows =
    ~940 s end-to-end, ~60% of it compile RTTs), so 20M+ would outrun any
    supervisor budget; 8M keeps a COMPLETE artifact inside the 2400 s child
    budget. `BENCH_LINEITEM_ROWS=20000000` opts into the full scale target on
    hardware with a local chip."""
    default_li = 8_000_000
    n_li = int(os.environ.get("BENCH_LINEITEM_ROWS", default_li))
    n_ord = int(os.environ.get("BENCH_ORDERS_ROWS", max(n_li // 8, 1000)))
    n_part = int(os.environ.get("BENCH_PART_ROWS", max(n_li // 20, 1000)))
    return n_li, n_ord, n_part


def _write_chunked(data: dict, path: str, n_files: int) -> None:
    """Write a pydict as `n_files` parquet files (multi-file sources are part of
    the scale contract: the scan path must concat + cache across files)."""
    from hyperspace_tpu.engine import io as eio
    from hyperspace_tpu.engine.table import Table

    n = len(next(iter(data.values())))
    per = (n + n_files - 1) // n_files
    for i in range(n_files):
        sl = slice(i * per, min((i + 1) * per, n))
        if sl.start >= n:
            break
        chunk = {k: v[sl] for k, v in data.items()}
        eio.write_parquet(
            Table.from_pydict(chunk), os.path.join(path, f"part-{i:05d}.parquet")
        )


_print_lock = threading.Lock()


class _PhaseAbort(Exception):
    """Raised at a measurement checkpoint to abandon the rest of a phase
    (deadline passed, or the relay transport died mid-phase)."""

    def __init__(self, reason: str):
        super().__init__(reason)
        self.reason = reason


class _Phases:
    """Accumulates phase results + errors; emits a BENCH_PARTIAL line after each
    completed phase AND at every `checkpoint()` inside the long phases, so a
    supervising parent salvages individual measurements, not just whole phases
    (the round-5 relay death lost the builds/indexed numbers exactly this way).

    Also enforces the CHILD-SIDE deadline: a slow child must END ITSELF inside
    its budget (skipping remaining phases, final record emitted, process exits
    cleanly = clean claim release) — the parent killing a claim-holding child
    is the known terminal-wedge trigger (TPU_EVIDENCE.md), so the parent's kill
    is strictly a last resort for a truly hung child.

    Transport-death gate: once any phase error carries a connection-refused
    signature, the relay PROCESS is gone (observed round 5: port 8083 stopped
    listening mid-bench) and every further device call either fails or hangs
    in a PJRT reconnect loop — so all remaining device phases are skipped and
    the child exits with what it has. Host-only phases still run."""

    def __init__(self, backend: str, deadline: float = None):
        self.out = {"backend": backend, "phase_errors": {}}
        self.deadline = deadline
        self.device = backend != "cpu"
        # Partial snapshots exist for the supervising parent; the in-process
        # CPU fallback has no supervisor, so it keeps stdout clean.
        self.emit = os.environ.get(_CHILD_ENV) == "1"

    def _emit(self) -> None:
        if self.emit:
            try:
                with _print_lock:
                    print(_PARTIAL_TAG + json.dumps(self.out), flush=True)
            except Exception:
                pass

    def transport_dead(self) -> bool:
        if self.out.get("relay_dead"):
            return True
        for v in self.out["phase_errors"].values():
            if "Connection refused" in v or "Connect error" in v:
                self.out["relay_dead"] = True
                return True
        return False

    def _abort_reason(self, host_only: bool = False):
        if self.deadline is not None and _now() > self.deadline:
            return "child-deadline"
        if not host_only and self.device and self.transport_dead():
            return "relay-dead"
        return None

    def checkpoint(self) -> None:
        """Call between measurements inside a phase: publishes everything
        measured so far, then aborts the phase tail if the budget is spent or
        the transport is dead (the abort is recorded as a skip, not an error)."""
        self._emit()
        reason = self._abort_reason()
        if reason:
            raise _PhaseAbort(reason)

    def run(self, name: str, fn, host_only: bool = False) -> bool:
        reason = self._abort_reason(host_only)
        if reason:
            self.out.setdefault("skipped_phases", []).append(name)
            self.out["aborted_at"] = reason
            return False
        try:
            fn()
            return True
        except _PhaseAbort as a:
            self.out.setdefault("skipped_phases", []).append(f"{name} (tail)")
            self.out["aborted_at"] = a.reason
            return False
        except Exception as e:
            import traceback

            self.out["phase_errors"][name] = (
                f"{type(e).__name__}: {e} @ "
                + traceback.format_exc(limit=3).splitlines()[-2].strip()
            )
            return False
        finally:
            self._emit()


def _metric_from(d: dict, rows_label: str = None) -> dict:
    """Build the driver-facing metric record from whatever measurements exist.
    Degrades honestly: build+join when both exist, else the best single number
    — never a fabricated 0.0 (the round-5 salvage emitted value 0.0 when the
    relay died before the builds phase)."""
    rows = rows_label or str(d.get("rows", "?"))
    build = d.get("build_s")
    idx = d.get("indexed_join_p50_s")
    scan = d.get("scan_join_p50_s")
    partial = (
        " (partial)"
        if ("aborted_at" in d or d.get("skipped_phases") or d.get("phase_errors"))
        else ""
    )
    if build is not None and idx is not None:
        name, value = f"tpch({rows}) index-build+join-p50{partial}", build + idx
    elif idx is not None:
        name, value = f"tpch({rows}) indexed-join-p50{partial}", idx
    elif build is not None:
        # Device phase order runs builds first: a transport death during the
        # indexed join leaves build-only partials — still a real measurement.
        name, value = f"tpch({rows}) index-build{partial}", build
    elif scan is not None:
        name, value = f"tpch({rows}) scan-join-p50{partial}", scan
    else:
        name, value = f"tpch({rows}) no-measurement{partial}", 0.0
    vs = round(scan / idx, 3) if (idx and scan) else None
    return {
        "metric": name,
        "value": round(value, 3),
        "unit": "s",
        "vs_baseline": vs,
        "detail": d,
    }


# Written by run_bench so the overrun watchdog (in _child_main) can salvage
# the current measurement dict even while the main thread is blocked inside a
# PJRT call that will never return — and clean up the bench tempdir, which
# run_bench's `finally` cannot do across os._exit.
_LIVE_PHASES: list = []
_BENCH_TMPDIR: list = []
# run_bench deposits its completed result here BEFORE its teardown (the
# tempdir rmtree takes seconds at 8M): the watchdog must not mistake a
# finished run still in teardown for a hung one.
_BENCH_RESULT: list = []


def run_bench(deadline: float = None) -> dict:
    from hyperspace_tpu import IndexConfig, IndexConstants
    from hyperspace_tpu.engine import HyperspaceSession, col
    from hyperspace_tpu.hyperspace import Hyperspace, disable_hyperspace, enable_hyperspace

    import jax

    backend = jax.devices()[0].platform
    n_li, n_ord, n_part = _sizes(backend)
    num_buckets = int(os.environ.get("BENCH_NUM_BUCKETS", 64))
    runs = int(os.environ.get("BENCH_RUNS", 3))

    ph = _Phases(backend, deadline)
    _LIVE_PHASES.append(ph)
    d = ph.out
    d["rows"] = n_li
    base = tempfile.mkdtemp(prefix="hs_bench_")
    _BENCH_TMPDIR.append(base)
    try:
        s = HyperspaceSession(warehouse=base)
        s.conf.set(IndexConstants.INDEX_SYSTEM_PATH, os.path.join(base, "indexes"))
        s.conf.set(IndexConstants.INDEX_NUM_BUCKETS, num_buckets)
        hs = Hyperspace(s)
        rng = np.random.RandomState(42)

        def gen_data():
            t0 = _now()
            _write_chunked(
                {
                    "orderkey": rng.randint(0, n_ord, n_li).astype(np.int64),
                    "partkey": rng.randint(0, n_part, n_li).astype(np.int64),
                    "qty": rng.randint(1, 51, n_li).astype(np.int64),
                    "price": (rng.rand(n_li) * 1000).astype(np.float64),
                    "discount": (rng.randint(0, 11, n_li) / 100.0),
                    "tax": (rng.randint(0, 9, n_li) / 100.0),
                    "shipdate": rng.randint(0, 2526, n_li).astype(np.int64),
                },
                os.path.join(base, "lineitem"),
                16,
            )
            _write_chunked(
                {
                    "o_orderkey": np.arange(n_ord, dtype=np.int64),
                    "o_custkey": rng.randint(0, max(n_ord // 25, 100), n_ord).astype(np.int64),
                },
                os.path.join(base, "orders"),
                4,
            )
            types = np.array(
                [f"{'PROMO' if i % 5 == 0 else 'STD'} TYPE#{i:02d}" for i in range(25)]
            )
            _write_chunked(
                {
                    "p_partkey": np.arange(n_part, dtype=np.int64),
                    "p_type": types[np.arange(n_part) % 25],
                },
                os.path.join(base, "part"),
                2,
            )
            d["datagen_s"] = round(_now() - t0, 1)
            d["source_bytes"] = _tree_bytes(
                *(os.path.join(base, t) for t in ("lineitem", "orders", "part"))
            )

        ph.run("datagen", gen_data, host_only=True)

        def q3():
            l = s.read.parquet(os.path.join(base, "lineitem"))
            o = s.read.parquet(os.path.join(base, "orders"))
            return (
                l.join(o, col("orderkey") == col("o_orderkey"))
                .with_column("revenue", col("price") * (1 - col("discount")))
                .group_by("o_custkey")
                .agg(revenue=("revenue", "sum"), n=("qty", "count"))
                .order_by(("revenue", False))
                .limit(10)
            )

        def q3_join_only():
            l = s.read.parquet(os.path.join(base, "lineitem"))
            o = s.read.parquet(os.path.join(base, "orders"))
            return l.join(o, col("orderkey") == col("o_orderkey")).select("qty", "o_custkey")

        def q14():
            l = s.read.parquet(os.path.join(base, "lineitem"))
            p = s.read.parquet(os.path.join(base, "part"))
            return (
                l.filter((col("shipdate") >= 1000) & (col("shipdate") < 1030))
                .join(p, col("partkey") == col("p_partkey"))
                .with_column("revenue", col("price") * (1 - col("discount")))
                .group_by("p_type")
                .agg(revenue=("revenue", "sum"))
                .order_by(("revenue", False))
                .limit(5)
            )

        # -- baselines: non-indexed sort-merge joins ------------------------
        def baselines():
            disable_hyperspace(s)
            q3_join_only().count()  # warm-up compile + scan-cache fill
            d["scan_join_p50_s"] = round(timed_p50(lambda: q3_join_only().count(), runs), 3)
            ph.checkpoint()
            q3().collect()
            d["agg_scan_p50_s"] = round(timed_p50(lambda: q3().collect(), runs), 3)
            ph.checkpoint()
            q14().collect()
            d["q14_scan_p50_s"] = round(timed_p50(lambda: q14().collect(), runs), 3)

        # -- index builds ---------------------------------------------------
        def builds():
            from hyperspace_tpu.telemetry.profiling import build_stages_history

            t0 = _now()
            hs.create_index(
                s.read.parquet(os.path.join(base, "lineitem")),
                IndexConfig("liIdx", ["orderkey"], ["qty", "price", "discount"]),
            )
            hs.create_index(
                s.read.parquet(os.path.join(base, "orders")),
                IndexConfig("ordIdx", ["o_orderkey"], ["o_custkey"]),
            )
            d["build_s"] = round(_now() - t0, 3)
            # Stage-level decode/h2d/sort/write timings + overlap ratio of the
            # two builds above (the headline metric's builds), newest last.
            d["build_stages"] = build_stages_history()[-2:]
            ph.checkpoint()
            t0 = _now()
            hs.create_index(
                s.read.parquet(os.path.join(base, "lineitem")),
                IndexConfig("liPartIdx", ["partkey"], ["price", "discount", "shipdate"]),
            )
            hs.create_index(
                s.read.parquet(os.path.join(base, "part")),
                IndexConfig("partIdx", ["p_partkey"], ["p_type"]),
            )
            d["build_q14_s"] = round(_now() - t0, 3)
            d["build_q14_stages"] = build_stages_history()[-2:]

        # -- indexed queries (join headline, then the aggregates) -----------
        def indexed_join():
            enable_hyperspace(s)
            t0 = _now()
            rows_indexed = q3_join_only().count()  # warm-up + correctness probe
            d["indexed_cold_s"] = round(_now() - t0, 3)
            ph.checkpoint()
            disable_hyperspace(s)
            rows_scan = q3_join_only().count()
            assert rows_indexed == rows_scan, (rows_indexed, rows_scan)
            d["join_rows"] = rows_indexed
            enable_hyperspace(s)
            d["indexed_join_p50_s"] = round(
                timed_p50(lambda: q3_join_only().count(), runs), 3
            )
            d["io_s"] = round(max(0.0, d["indexed_cold_s"] - d["indexed_join_p50_s"]), 3)

        def indexed_agg():
            enable_hyperspace(s)
            q3().collect()
            d["agg_indexed_p50_s"] = round(timed_p50(lambda: q3().collect(), runs), 3)
            ph.checkpoint()
            d["q14_uses_index"] = "liPartIdx" in q14().explain_string()
            q14().collect()
            d["q14_indexed_p50_s"] = round(timed_p50(lambda: q14().collect(), runs), 3)
            ph.checkpoint()
            # Q14 correctness: identical top rows with indexing on vs off.
            top_on = q14().collect().rows()
            disable_hyperspace(s)
            top_off = q14().collect().rows()
            enable_hyperspace(s)
            assert [r[0] for r in top_on] == [r[0] for r in top_off]

        # Phase order is backend-dependent: on a relay-backed device the
        # headline measurements (builds + indexed join — the driver's metric)
        # go FIRST so a mid-run transport death still yields them; the round-5
        # relay died ~4 min in, after baselines but before builds, and the
        # artifact had no headline. On CPU (no transport to lose) the scan
        # baselines run first so the builds/indexed phases inherit a warm
        # scan cache exactly as in every prior round's artifact.
        if backend == "cpu":
            order = [("baselines", baselines), ("builds", builds),
                     ("indexed_join", indexed_join), ("indexed_agg", indexed_agg)]
        else:
            order = [("builds", builds), ("indexed_join", indexed_join),
                     ("baselines", baselines), ("indexed_agg", indexed_agg)]
        for name, fn in order:
            ph.run(name, fn)
        if d.get("agg_indexed_p50_s") and d.get("agg_scan_p50_s"):
            d["agg_speedup"] = round(d["agg_scan_p50_s"] / d["agg_indexed_p50_s"], 3)
        if d.get("q14_indexed_p50_s") and d.get("q14_scan_p50_s"):
            d["q14_speedup"] = round(d["q14_scan_p50_s"] / d["q14_indexed_p50_s"], 3)

        # -- measured device kernels + cache pressure ----------------------
        ph.run("device", lambda: d.update(_device_section(s, base, col, runs, backend)))
        # -- device cost observatory: the same engine under probes — per-label
        #    device time, transfer + padding ledgers, effective GB/s vs a
        #    measured memcpy peak
        ph.run("device_observatory", lambda: d.__setitem__(
            "device_observatory", _device_observatory_section(s, base, col, runs)
        ))
        ph.run(
            "eviction_stress",
            lambda: d.update(_eviction_stress(s, q3_join_only, d)),
        )

        # -- streaming scan→filter→aggregate (chunked decode overlap) --------
        def stream_agg():
            d.update(_stream_agg_section(s, base, col, runs))

        ph.run("stream_agg", stream_agg)

        # -- streamed bucketed-join→aggregate (classed probe + chunked gather)
        ph.run("join_stream", lambda: d.update(_join_stream_section(s, base, col, runs)))

        # -- multiway star join: 1 fact + 3 covered dims in ONE streamed
        #    pass vs the cascaded binary joins (cold/warm p50 + per-dim
        #    probe/verify stage walls); gated by bench_compare --keys 'star*'
        ph.run("star_join", lambda: d.__setitem__(
            "star_join", _star_section(s, base, col, runs, hs)
        ))

        # -- workload variants (string join / filter / data skipping / hybrid)
        ph.run("variants", lambda: d.__setitem__(
            "variants", _variant_section(s, base, col, runs, hs)
        ))
        # -- scan pushdown: row-group pruning on clustered data (cold on/off
        #    splits + the row-group/byte counters that prove the prune)
        ph.run("scan_pushdown", lambda: d.update(_pushdown_section(s, base, col, runs, hs)))
        # -- encoded execution: dictionary-code string keys kept as codes
        #    through scan/build/join (cold on/off splits + effective GB/s +
        #    the encoded/materialized byte counters that prove the path)
        ph.run("encoded_exec", lambda: d.update(_encoded_section(s, base, col, runs, hs)))
        # -- device-resident codes: narrow code lanes across the H2D boundary
        #    and the mesh exchange (flag on/off H2D + bytes_moved reductions)
        ph.run(
            "encoded_device",
            lambda: d.update(_encoded_device_section(s, base, col, runs, hs)),
        )
        # -- bit-packed sub-byte code lanes: 1/2/4-bit packing below int8
        #    across H2D + probe-on-packed vs widen-then-probe (the mesh
        #    exchange half runs in the forced-8-device child; _finish folds
        #    it into this section)
        ph.run(
            "packed_codes",
            lambda: d.update(_packed_codes_section(s, base, col, runs, hs)),
        )
        # -- adaptive planner: every ambient knob UNSET (planner deciding)
        #    vs the best hand-picked pinned configuration per workload
        ph.run("planner", lambda: d.update(_planner_section(s, base, col, runs, hs)))
        # -- stage-level attribution: mispriced knob + unrelated decode
        #    slowdown; stage-grain learning flips within the leg, whole-wall
        #    learning does not (plus the ledger's on/off overhead p50s)
        ph.run(
            "attribution",
            lambda: d.update(_attribution_section(s, base, col, runs, hs)),
        )
        # -- multi-tenant serving: N clients × mixed Q1/Q3/Q14/point workload
        #    through the QueryServer (throughput, per-class p50/p99, dedup
        #    counters, cold-scan single-flight probe)
        ph.run("serving", lambda: d.update(_serving_section(s, base, col, runs, hs)))
        # -- live tables: streaming ingest (append batches + incremental
        #    refresh + background compaction) landing WHILE the interactive
        #    mix runs — staleness, refresh latency, and interactive p50/p99
        #    before/during/after refresh and compaction
        ph.run("live_tables", lambda: d.update(_live_tables_section(s, base, col, runs, hs)))
        # -- replica fleet: K serving subprocesses over ONE shared lake —
        #    on-lake registry + rendezvous decode routing; aggregate qps
        #    1→2→3, cross-replica cold-decode dedup, byte-identity vs the
        #    HYPERSPACE_REPLICAS=0 fallback (docs/serving.md "Replica fleet")
        ph.run(
            "replicas",
            lambda: d.update(_replica_section(s, base, col, runs, hs)),
            host_only=True,
        )
        # Cache stats AFTER the variants: the hybrid-scan queries are the
        # per-file scan cache's real workload (query-time re-reads the higher
        # cache levels cannot hold).
        ph.run("caches", lambda: d.update(_cache_section()), host_only=True)

        # A deadline/transport abort must never masquerade as a complete run:
        # _metric_from carries the partial marker and degrades to the best
        # available single measurement (same contract as the parent's salvage).
        res = _metric_from(d, rows_label=f"{n_li}x{n_ord}")
        _BENCH_RESULT.append(res)
        return res
    finally:
        shutil.rmtree(base, ignore_errors=True)


def _stream_agg_section(s, base, col, runs) -> dict:
    """The streaming executor's own shape — scan→filter→aggregate over the
    16-file lineitem source, no join — measured COLD (scan caches cleared)
    with streaming on vs the materialized fallback, plus the warm streaming
    p50. The cold delta is the decode-overlap win; `query_stages` records the
    per-stage busy times + overlap ratio of the streaming cold run."""
    from hyperspace_tpu.engine.scan_cache import (
        global_concat_cache,
        global_scan_cache,
    )
    from hyperspace_tpu.telemetry.profiling import last_query_stages

    def qsa():
        l = s.read.parquet(os.path.join(base, "lineitem"))
        return (
            l.filter(col("shipdate") < 1263)
            .group_by("shipdate")
            .agg(rev=("price", "sum"), n=("qty", "count"))
        )

    env_key = "HYPERSPACE_QUERY_STREAMING"
    saved = os.environ.get(env_key)

    def run_cold(streaming: bool) -> float:
        global_scan_cache().clear()
        global_concat_cache().clear()
        os.environ[env_key] = "1" if streaming else "0"
        t0 = _now()
        qsa().collect()
        return round(_now() - t0, 3)

    out = {}
    try:
        out["agg_stream_cold_s"] = run_cold(True)
        out["query_stages"] = last_query_stages()
        out["agg_mat_cold_s"] = run_cold(False)
        os.environ[env_key] = "1"
        qsa().collect()  # warm the per-file cache for the steady-state p50
        out["agg_stream_warm_p50_s"] = round(
            timed_p50(lambda: qsa().collect(), runs), 3
        )
    finally:
        if saved is None:
            os.environ.pop(env_key, None)
        else:
            os.environ[env_key] = saved
    return out


def _join_stream_section(s, base, col, runs) -> dict:
    """The streamed join→aggregate's own shape — the Q3 aggregate over the
    covering indexes — measured COLD (scan caches + device memos cleared) with
    streaming on vs the materialized fallback, plus the warm streamed p50.
    `join_stages` records the per-stage busy times, overlap ratio, class/
    outlier counts and pallas fallback counters of the streamed cold run."""
    from hyperspace_tpu.engine.physical import clear_device_memos
    from hyperspace_tpu.engine.scan_cache import (
        global_bucketed_cache,
        global_concat_cache,
        global_scan_cache,
    )
    from hyperspace_tpu.hyperspace import enable_hyperspace
    from hyperspace_tpu.telemetry.profiling import last_join_stages

    enable_hyperspace(s)

    def qja():
        l = s.read.parquet(os.path.join(base, "lineitem"))
        o = s.read.parquet(os.path.join(base, "orders"))
        return (
            l.join(o, col("orderkey") == col("o_orderkey"))
            .with_column("revenue", col("price") * (1 - col("discount")))
            .group_by("o_custkey")
            .agg(revenue=("revenue", "sum"), n=("qty", "count"))
        )

    env_key = "HYPERSPACE_QUERY_STREAMING"
    saved = os.environ.get(env_key)

    def run_cold(streaming: bool) -> float:
        clear_device_memos()
        global_scan_cache().clear()
        global_concat_cache().clear()
        global_bucketed_cache().clear()
        os.environ[env_key] = "1" if streaming else "0"
        t0 = _now()
        qja().collect()
        return round(_now() - t0, 3)

    out = {}
    try:
        out["join_stream_cold_s"] = run_cold(True)
        out["join_stages"] = last_join_stages()
        out["join_mat_cold_s"] = run_cold(False)
        os.environ[env_key] = "1"
        clear_device_memos()
        qja().collect()  # warm the pairs memo for the steady-state p50
        out["join_stream_warm_p50_s"] = round(
            timed_p50(lambda: qja().collect(), runs), 3
        )
    finally:
        if saved is None:
            os.environ.pop(env_key, None)
        else:
            os.environ[env_key] = saved
    return out


def _star_section(s, base, col, runs, hs) -> dict:
    """Multiway star-join execution (the ISSUE-18 headline): one skewed-FK
    fact (written as FOUR parquet parts, so the concat identity keeps the
    per-dimension pair memos warm across queries) joined to THREE covered
    dimensions under a grouped aggregate — measured COLD (caches + memos
    cleared) and warm-p50 with ``HYPERSPACE_MULTIWAY`` on (one streamed
    pass probing every dimension per fact chunk) vs off (the cascaded
    binary joins, whose intermediate fact materializes once per extra
    dimension). ``star_stages`` records the multiway cold run's per-
    dimension pad/probe/verify walls and memo states (`star_dims`)."""
    from hyperspace_tpu import IndexConfig
    from hyperspace_tpu.engine import io as _eio
    from hyperspace_tpu.engine.physical import clear_device_memos
    from hyperspace_tpu.engine.scan_cache import (
        global_bucketed_cache,
        global_concat_cache,
        global_scan_cache,
    )
    from hyperspace_tpu.engine.table import Table as _T
    from hyperspace_tpu.hyperspace import enable_hyperspace
    from hyperspace_tpu.telemetry.profiling import last_join_stages

    n = int(os.environ.get("BENCH_STAR_ROWS", 500_000))
    rng = np.random.RandomState(31)
    dims = (("star_dim1", "d1", "g1", 1000), ("star_dim2", "d2", "g2", 200),
            ("star_dim3", "d3", "g3", 50))
    fk = {
        "k1": rng.randint(0, 1000, n).astype(np.int64),
        "k2": rng.randint(0, 200, n).astype(np.int64),
        "k3": rng.randint(0, 50, n).astype(np.int64),
        "amount": rng.randint(0, 10_000, n).astype(np.int64),
    }
    fk["k1"][: n // 3] = 17  # hot key: the skew the classed layout absorbs
    fact_dir = os.path.join(base, "star_fact")
    parts, per = 4, n // 4
    for i in range(parts):
        sl = {k: v[i * per: n if i == parts - 1 else (i + 1) * per]
              for k, v in fk.items()}
        _eio.write_parquet(
            _T.from_pydict(sl), os.path.join(fact_dir, f"part-{i:05d}.parquet")
        )
    for name, key, payload, card in dims:
        s.write_parquet(
            {
                key: np.arange(card, dtype=np.int64),
                payload: rng.randint(0, 25, card).astype(np.int64),
            },
            os.path.join(base, name),
        )
        hs.create_index(
            s.read.parquet(os.path.join(base, name)),
            IndexConfig(f"bench_{name}", [key], [payload]),
        )
    enable_hyperspace(s)

    def q_star():
        f = s.read.parquet(fact_dir)
        t = f
        for name, key, _payload, _card in dims:
            d = s.read.parquet(os.path.join(base, name))
            t = t.join(d, col(f"k{name[-1]}") == col(key))
        return t.group_by("g1").agg(
            rev=("amount", "sum"), n=("amount", "count")
        )

    env_mw, env_stream = "HYPERSPACE_MULTIWAY", "HYPERSPACE_QUERY_STREAMING"
    saved = {k: os.environ.get(k) for k in (env_mw, env_stream)}

    def run_cold(multiway: bool) -> float:
        clear_device_memos()
        global_scan_cache().clear()
        global_concat_cache().clear()
        global_bucketed_cache().clear()
        os.environ[env_mw] = "1" if multiway else "0"
        t0 = _now()
        q_star().collect()
        return round(_now() - t0, 3)

    out = {}
    try:
        os.environ[env_stream] = "1"
        out["star_multiway_cold_s"] = run_cold(True)
        out["star_stages"] = last_join_stages()
        out["star_cascade_cold_s"] = run_cold(False)

        os.environ[env_mw] = "1"
        clear_device_memos()
        q_star().collect()  # warm the per-dimension pair memos
        out["star_multiway_warm_p50_s"] = round(
            timed_p50(lambda: q_star().collect(), runs), 3
        )
        os.environ[env_mw] = "0"
        clear_device_memos()
        q_star().collect()  # warm the cascade's own pair memos
        out["star_cascade_warm_p50_s"] = round(
            timed_p50(lambda: q_star().collect(), runs), 3
        )
        if out["star_multiway_warm_p50_s"]:
            out["star_speedup"] = round(
                out["star_cascade_warm_p50_s"] / out["star_multiway_warm_p50_s"], 3
            )
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    return out


def _pushdown_section(s, base, col, runs, hs) -> dict:
    """The scan pushdown's own shapes, on CLUSTERED multi-row-group data
    (zone maps only help when values correlate with layout — the headline
    lineitem columns are uniform-random by design):

    - a selective range filter+aggregate over a 4-file ascending-ts source,
      measured COLD with ``HYPERSPACE_SCAN_PUSHDOWN`` on vs off (the
      whole-file fallback), plus the warm p50;
    - an indexed point lookup whose bucket file was built with bounded
      key-sorted row groups (``HYPERSPACE_INDEX_ROW_GROUP_ROWS``), pruning
      INSIDE the one file bucket pruning leaves.

    ``io_pruning`` carries the measured row-group/byte counters of the
    pushdown-ON cold runs — the proof the win is fewer bytes decoded."""
    from hyperspace_tpu import IndexConfig
    from hyperspace_tpu.engine import io as _eio
    from hyperspace_tpu.engine.scan_cache import (
        global_concat_cache,
        global_filtered_cache,
        global_scan_cache,
    )
    from hyperspace_tpu.engine.table import Table as _T
    from hyperspace_tpu.hyperspace import disable_hyperspace, enable_hyperspace
    from hyperspace_tpu.telemetry import metrics
    from hyperspace_tpu.telemetry.profiling import io_pruning_summary

    n = int(os.environ.get("BENCH_PUSHDOWN_ROWS", 1_000_000))
    files, rg_per_file = 4, 8
    per = n // files
    pd_dir = os.path.join(base, "events_pd")
    rng = np.random.RandomState(11)
    for i in range(files):
        _eio.write_parquet(
            _T.from_pydict(
                {
                    "ts": (np.arange(per, dtype=np.int64) + i * per),
                    "val": rng.randint(0, 1000, per).astype(np.int64),
                }
            ),
            os.path.join(pd_dir, f"part-{i:05d}.parquet"),
            row_group_rows=max(1, per // rg_per_file),
        )

    def q_range():
        lo = 3 * per + per // 3
        return (
            s.read.parquet(pd_dir)
            .filter((col("ts") >= lo) & (col("ts") < lo + per // 8))
            .group_by("val")
            .agg(n=("ts", "count"))
        )

    env_key = "HYPERSPACE_SCAN_PUSHDOWN"
    saved = os.environ.get(env_key)

    def clear():
        global_scan_cache().clear()
        global_concat_cache().clear()
        global_filtered_cache().clear()

    def run_cold(make_q, on: bool) -> float:
        clear()
        os.environ[env_key] = "1" if on else "0"
        t0 = _now()
        make_q().collect()
        return round(_now() - t0, 3)

    def counters():
        return {
            k: metrics.counter(f"io.pruning.{k}").value
            for k in ("row_groups_scanned", "row_groups_skipped", "bytes_decoded", "bytes_skipped")
        }

    out = {}
    try:
        disable_hyperspace(s)
        c0 = counters()
        out["pushdown_scan_cold_s"] = run_cold(q_range, True)
        c1 = counters()
        out["scan_pruning"] = {k: c1[k] - c0[k] for k in c1}
        out["nopushdown_scan_cold_s"] = run_cold(q_range, False)
        os.environ[env_key] = "1"
        q_range().collect()
        out["pushdown_scan_warm_p50_s"] = round(
            timed_p50(lambda: q_range().collect(), runs), 4
        )

        # Indexed point lookup: bounded row groups inside the bucket files.
        saved_rg = os.environ.get(_eio.ENV_INDEX_ROW_GROUP_ROWS)
        os.environ[_eio.ENV_INDEX_ROW_GROUP_ROWS] = "2048"
        try:
            hs.create_index(
                s.read.parquet(pd_dir), IndexConfig("vPdIdx", ["ts"], ["val"])
            )
        finally:
            if saved_rg is None:
                os.environ.pop(_eio.ENV_INDEX_ROW_GROUP_ROWS, None)
            else:
                os.environ[_eio.ENV_INDEX_ROW_GROUP_ROWS] = saved_rg
        enable_hyperspace(s)
        probe = 2 * per + 777

        def q_point():
            return s.read.parquet(pd_dir).filter(col("ts") == probe).select("val")

        out["point_uses_index"] = "vPdIdx" in q_point().explain_string()
        c0 = counters()
        out["pushdown_point_cold_s"] = run_cold(q_point, True)
        c1 = counters()
        out["point_pruning"] = {k: c1[k] - c0[k] for k in c1}
        out["nopushdown_point_cold_s"] = run_cold(q_point, False)
        os.environ[env_key] = "1"
        q_point().collect()
        out["pushdown_point_warm_p50_s"] = round(
            timed_p50(lambda: q_point().collect(), runs), 4
        )
        disable_hyperspace(s)
    finally:
        if saved is None:
            os.environ.pop(env_key, None)
        else:
            os.environ[env_key] = saved
    out["io_pruning_totals"] = io_pruning_summary()
    return {"io_pruning": out}


def _encoded_section(s, base, col, runs, hs) -> dict:
    """Encoded execution's own shapes, on a dictionary-heavy string-keyed
    source (moderate cardinality — exactly where keeping codes beats
    flattening):

    - a cold multi-file scan + string-key aggregate, measured with
      ``HYPERSPACE_ENCODED_EXEC`` on vs off (the flatten fallback), with the
      on-disk byte total → EFFECTIVE GB/s for both modes;
    - a cold covering-index build on the string key (dictionary-preserving
      bucket writes vs N-string decode per bucket);
    - the indexed string-key join p50, warm, on vs off.

    ``encoded_bytes`` carries the measured byte-split and per-column
    counters of the ON runs — the proof the win is bytes not moved."""
    from hyperspace_tpu import IndexConfig
    from hyperspace_tpu.engine import io as _eio
    from hyperspace_tpu.engine.physical import clear_device_memos
    from hyperspace_tpu.engine.scan_cache import (
        global_bucketed_cache,
        global_concat_cache,
        global_filtered_cache,
        global_scan_cache,
    )
    from hyperspace_tpu.engine.table import Table as _T
    from hyperspace_tpu.hyperspace import disable_hyperspace, enable_hyperspace
    from hyperspace_tpu.telemetry import metrics

    n = int(os.environ.get("BENCH_ENCODED_ROWS", 2_000_000))
    n_dim = max(n // 8, 1000)
    card = max(min(n // 20, 100_000), 100)
    files = 4
    enc_dir = os.path.join(base, "events_enc")
    dim_dir = os.path.join(base, "dim_enc")
    rng = np.random.RandomState(13)
    dictionary = np.asarray([f"cust#{i:08d}" for i in range(card)])
    for i in range(files):
        per = n // files
        _eio.write_parquet(
            _T.from_pydict(
                {
                    "k": dictionary[rng.randint(0, card, per)].tolist(),
                    "v": rng.randint(0, 1000, per).astype(np.int64).tolist(),
                }
            ),
            os.path.join(enc_dir, f"part-{i:05d}.parquet"),
        )
    _eio.write_parquet(
        _T.from_pydict(
            {
                "k": dictionary[rng.randint(0, card, n_dim)].tolist(),
                "w": rng.randint(0, 100, n_dim).astype(np.int64).tolist(),
            }
        ),
        os.path.join(dim_dir, "part-00000.parquet"),
    )
    # The scan query reads enc_dir ONLY — its effective-GB/s denominator must
    # not be credited with the dim file's bytes.
    scan_src_bytes = _tree_bytes(enc_dir)
    src_bytes = scan_src_bytes + _tree_bytes(dim_dir)

    def q_scan():
        return (
            s.read.parquet(enc_dir)
            .group_by("k")
            .agg(total=("v", "sum"), cnt=("v", "count"))
        )

    def q_join():
        return s.read.parquet(enc_dir).join(
            s.read.parquet(dim_dir), col("k") == col("k")
        )

    env_key = "HYPERSPACE_ENCODED_EXEC"
    saved = os.environ.get(env_key)

    def clear():
        global_scan_cache().clear()
        global_concat_cache().clear()
        global_filtered_cache().clear()
        global_bucketed_cache().clear()
        clear_device_memos()

    def counters():
        return {
            k: metrics.counter(name).value
            for k, name in (
                ("bytes_encoded_kept", "io.pruning.bytes_encoded_kept"),
                ("bytes_materialized", "io.pruning.bytes_materialized"),
                ("columns_encoded", "io.encoded.columns_encoded"),
                ("columns_flattened", "io.encoded.columns_flattened"),
                ("columns_dict_written", "io.encoded.columns_dict_written"),
                ("scan_encoded_hits", "cache.scan.encoded_hits"),
            )
        }

    out = {}
    try:
        disable_hyperspace(s)
        for label, flag in (("on", "1"), ("off", "0")):
            os.environ[env_key] = flag
            clear()
            c0 = counters()
            t0 = _now()
            q_scan().collect()
            dt = _now() - t0
            out[f"scan_cold_{label}_s"] = round(dt, 3)
            out[f"scan_cold_{label}_gbps"] = round(
                scan_src_bytes / max(dt, 1e-9) / 1e9, 3
            )
            if label == "on":
                c1 = counters()
                out["scan_counters"] = {k: c1[k] - c0[k] for k in c1}
        os.environ[env_key] = "1"
        q_scan().collect()  # warm per-file cache for the steady-state p50
        out["scan_warm_p50_s"] = round(timed_p50(lambda: q_scan().collect(), runs), 4)

        for label, flag in (("on", "1"), ("off", "0")):
            os.environ[env_key] = flag
            clear()
            c0 = counters()
            t0 = _now()
            hs.create_index(
                s.read.parquet(enc_dir), IndexConfig(f"encK{label}", ["k"], ["v"])
            )
            hs.create_index(
                s.read.parquet(dim_dir), IndexConfig(f"encD{label}", ["k"], ["w"])
            )
            out[f"build_cold_{label}_s"] = round(_now() - t0, 3)
            if label == "on":
                out["build_counters"] = {
                    k: v - c0[k] for k, v in counters().items()
                }
            enable_hyperspace(s)
            clear()
            rows = q_join().count()  # cold indexed pass (also correctness probe)
            out.setdefault("join_rows", rows)
            assert out["join_rows"] == rows, (out["join_rows"], rows)
            out[f"join_p50_{label}_s"] = round(
                timed_p50(lambda: q_join().count(), runs), 4
            )
            disable_hyperspace(s)
            hs.delete_index(f"encK{label}"), hs.vacuum_index(f"encK{label}")
            hs.delete_index(f"encD{label}"), hs.vacuum_index(f"encD{label}")
        out["src_bytes"] = src_bytes
        out["scan_src_bytes"] = scan_src_bytes
        # Rows actually written: files * (n // files) — the floor division
        # drops a remainder when BENCH_ENCODED_ROWS isn't a multiple of files.
        out["rows"] = files * (n // files)
        out["key_cardinality"] = card
    finally:
        if saved is None:
            os.environ.pop(env_key, None)
        else:
            os.environ[env_key] = saved
    return {"encoded_exec": out}


def _encoded_device_section(s, base, col, runs, hs) -> dict:
    """Device-resident codes (`HYPERSPACE_ENCODED_DEVICE`): how many bytes the
    narrow code lanes keep OFF the host→device boundary and the mesh wire, on
    a low-cardinality string-key workload (card 100 → int8 codes, the 4x
    narrowing class):

    - a cold string-key count-join measured with the flag on vs off, with the
      `transfer.h2d.bytes` delta for each mode → ``h2d_reduction_x``;
    - the pow2 padding split of the ON leg (payload vs padded bytes).

    The mesh half — `parallel.exchange.bytes_moved` on vs off → the
    ``bytes_moved_reduction_x`` the code-space exchange buys (flat 20 B/row
    send lanes vs coded 8 B/row) — needs a multi-device mesh, so it runs in
    `run_mesh_bench`'s forced-8-device child and `_finish` folds it into this
    section's dict.

    `tools/bench_compare.py --keys 'encoded_device*'` gates these: the two
    reduction ratios are higher-is-better counters, the seconds are timings."""
    from hyperspace_tpu.engine import io as _eio
    from hyperspace_tpu.engine.physical import clear_device_memos
    from hyperspace_tpu.engine.scan_cache import (
        global_bucketed_cache,
        global_concat_cache,
        global_filtered_cache,
        global_scan_cache,
    )
    from hyperspace_tpu.engine.table import Table as _T
    from hyperspace_tpu.hyperspace import disable_hyperspace
    from hyperspace_tpu.telemetry import metrics

    n = int(os.environ.get("BENCH_ENCODED_DEVICE_ROWS", 300_000))
    n_dim = max(n // 8, 1000)
    card = 100  # int8 code class — where narrowing bites hardest
    fact_dir = os.path.join(base, "fact_encdev")
    dim_dir = os.path.join(base, "dim_encdev")
    rng = np.random.RandomState(29)
    dictionary = np.asarray([f"sku#{i:04d}" for i in range(card)])
    _eio.write_parquet(
        _T.from_pydict(
            {
                "k": dictionary[rng.randint(0, card, n)].tolist(),
                "v": rng.randint(0, 1000, n).astype(np.int64).tolist(),
            }
        ),
        os.path.join(fact_dir, "part-00000.parquet"),
    )
    _eio.write_parquet(
        _T.from_pydict(
            {
                "k": dictionary[rng.randint(0, card, n_dim)].tolist(),
                "w": rng.randint(0, 100, n_dim).astype(np.int64).tolist(),
            }
        ),
        os.path.join(dim_dir, "part-00000.parquet"),
    )

    def q_join():
        return s.read.parquet(fact_dir).join(
            s.read.parquet(dim_dir), col("k") == col("k")
        )

    def clear():
        global_scan_cache().clear()
        global_concat_cache().clear()
        global_filtered_cache().clear()
        global_bucketed_cache().clear()
        clear_device_memos()

    def cval(name):
        return metrics.counter(name).value

    env_key = "HYPERSPACE_ENCODED_DEVICE"
    saved = os.environ.get(env_key)
    out = {"rows": n, "key_cardinality": card}
    try:
        disable_hyperspace(s)
        rows_seen = None
        for label, flag in (("on", "1"), ("off", "0")):
            os.environ[env_key] = flag
            clear()
            h0 = cval("transfer.h2d.bytes")
            p0 = cval("pad.bytes_payload"), cval("pad.bytes_padded")
            t0 = _now()
            rows = q_join().count()
            out[f"join_cold_{label}_s"] = round(_now() - t0, 3)
            out[f"h2d_bytes_{label}"] = cval("transfer.h2d.bytes") - h0
            if rows_seen is None:
                rows_seen = rows
            assert rows == rows_seen, (rows, rows_seen)  # flag oracle
            if label == "on":
                payload = cval("pad.bytes_payload") - p0[0]
                padded = cval("pad.bytes_padded") - p0[1]
                out["pad_ratio_on"] = round(
                    padded / max(payload + padded, 1), 4
                )
        out["join_rows"] = int(rows_seen)
        out["h2d_reduction_x"] = round(
            out["h2d_bytes_off"] / max(out["h2d_bytes_on"], 1), 2
        )
    finally:
        if saved is None:
            os.environ.pop(env_key, None)
        else:
            os.environ[env_key] = saved
    return {"encoded_device": out}


def _packed_codes_section(s, base, col, runs, hs) -> dict:
    """Bit-packed sub-byte code lanes (`HYPERSPACE_PACKED_CODES`): what 1/2/4-bit
    lane packing buys BELOW the int8 narrow-code floor, on a ≤16-distinct
    string-key join (card 12 → the 4-bit lane class):

    - a cold string-key count-join under encoded execution with packing on vs
      off: `transfer.h2d.bytes` per leg → ``h2d_reduction_x`` (the off leg is
      the PR-15 int8 narrow path — the ratio is packed vs int8, not vs flat);
    - the ON leg's `device_code_bytes_{flat,staged,packed}` deltas →
      ``bits_per_code`` actually charged on the wire;
    - a measured packed-words upload (64M 4-bit codes = 32 MiB of words)
      against the host memcpy peak → ``h2d_vs_memcpy_peak``;
    - probe-on-packed vs widen-then-probe p50 over `PackedCodeBuckets` reps
      (`ops.bucket_join.probe_code_ranges` auto dispatch vs the forced unpack
      fallback), with the resident rep bytes next to the int8 equivalent.

    The mesh half — `parallel.exchange.bytes_moved` with packing on vs off
    (sub-byte key/bucket/validity lanes + the 16-bit rowid wire class vs the
    int8 coded exchange) — needs a multi-device mesh; `run_mesh_bench`'s
    child measures it and `_finish` folds it in here.

    `tools/bench_compare.py --keys 'packed*'` gates these (self-gating: keys
    absent from both artifacts pass)."""
    import jax

    from hyperspace_tpu.engine import io as _eio
    from hyperspace_tpu.engine.packed_codes import pack_codes_host
    from hyperspace_tpu.engine.physical import clear_device_memos
    from hyperspace_tpu.engine.scan_cache import (
        global_bucketed_cache,
        global_concat_cache,
        global_filtered_cache,
        global_scan_cache,
    )
    from hyperspace_tpu.engine.table import Table as _T
    from hyperspace_tpu.hyperspace import disable_hyperspace
    from hyperspace_tpu.ops import bucket_join as _bj
    from hyperspace_tpu.telemetry import metrics

    n = int(os.environ.get("BENCH_PACKED_CODES_ROWS", 300_000))
    n_dim = max(n // 8, 1000)
    card = 12  # biased codes fit 4 bits; probe bound (card+2 <= 16) holds too
    fact_dir = os.path.join(base, "fact_packed")
    dim_dir = os.path.join(base, "dim_packed")
    rng = np.random.RandomState(31)
    dictionary = np.asarray([f"cat#{i:02d}" for i in range(card)])
    _eio.write_parquet(
        _T.from_pydict(
            {
                "k": dictionary[rng.randint(0, card, n)].tolist(),
                "v": rng.randint(0, 1000, n).astype(np.int64).tolist(),
            }
        ),
        os.path.join(fact_dir, "part-00000.parquet"),
    )
    _eio.write_parquet(
        _T.from_pydict(
            {
                "k": dictionary[rng.randint(0, card, n_dim)].tolist(),
                "w": rng.randint(0, 100, n_dim).astype(np.int64).tolist(),
            }
        ),
        os.path.join(dim_dir, "part-00000.parquet"),
    )

    def q_join():
        return s.read.parquet(fact_dir).join(
            s.read.parquet(dim_dir), col("k") == col("k")
        )

    def clear():
        global_scan_cache().clear()
        global_concat_cache().clear()
        global_filtered_cache().clear()
        global_bucketed_cache().clear()
        clear_device_memos()

    def cval(name):
        return int(metrics.counter(name).value)

    env_enc = "HYPERSPACE_ENCODED_DEVICE"
    env_pk = "HYPERSPACE_PACKED_CODES"
    saved = {k: os.environ.get(k) for k in (env_enc, env_pk)}
    out = {"rows": n, "key_cardinality": card}
    try:
        disable_hyperspace(s)
        os.environ[env_enc] = "1"  # both legs ride encoded execution
        rows_seen = None
        for label, flag in (("on", "1"), ("off", "0")):
            os.environ[env_pk] = flag
            clear()
            h0 = metrics.counter("transfer.h2d.bytes").value
            c0 = {
                k: cval(f"device.encoded.bytes_{k}")
                for k in ("flat", "staged", "packed")
            }
            t0 = _now()
            rows = q_join().count()
            out[f"join_cold_{label}_s"] = round(_now() - t0, 3)
            out[f"h2d_bytes_{label}"] = (
                metrics.counter("transfer.h2d.bytes").value - h0
            )
            if rows_seen is None:
                rows_seen = rows
            assert rows == rows_seen, (rows, rows_seen)  # flag oracle
            code = {
                k: cval(f"device.encoded.bytes_{k}") - c0[k]
                for k in ("flat", "staged", "packed")
            }
            out[f"code_bytes_{label}"] = code
            if label == "on" and code["packed"]:
                # Bits actually charged per code across every packed stage.
                out["bits_per_code"] = round(
                    code["packed"] * 8 / max(code["flat"] // 4, 1), 2
                )
        out["join_rows"] = int(rows_seen)
        out["h2d_reduction_x"] = round(
            out["h2d_bytes_off"] / max(out["h2d_bytes_on"], 1), 2
        )

        # -- measured packed upload vs the host memcpy peak ------------------
        n_up = 64 * 1024 * 1024  # 64M 4-bit codes -> 32 MiB of words
        codes_up = rng.randint(0, card, n_up).astype(np.int8)
        words_up = pack_codes_host(codes_up, 4)
        buf = np.ones(64 * 1024 * 1024 // 8, dtype=np.float64)
        dst = np.empty_like(buf)
        t0 = _now()
        np.copyto(dst, buf)
        memcpy_gbps = buf.nbytes / max(_now() - t0, 1e-9) / 1e9
        jax.device_put(words_up).block_until_ready()  # warm the path
        t0 = _now()
        jax.device_put(words_up).block_until_ready()
        h2d_gbps = words_up.nbytes / max(_now() - t0, 1e-9) / 1e9
        out["memcpy_peak_gbps"] = round(memcpy_gbps, 2)
        out["packed_h2d_gbps"] = round(h2d_gbps, 2)
        out["h2d_vs_memcpy_peak"] = round(h2d_gbps / max(memcpy_gbps, 1e-9), 4)

        # -- probe-on-packed vs widen-then-probe -----------------------------
        os.environ[env_pk] = "1"
        n_probe = int(os.environ.get("BENCH_PACKED_PROBE_ROWS", 120_000))
        B = 64
        l_lens = rng.randint(0, 2 * n_probe // B, B)
        r_lens = rng.randint(0, 2 * n_probe // B, B)
        l_starts = np.concatenate([[0], np.cumsum(l_lens)])
        r_starts = np.concatenate([[0], np.cumsum(r_lens)])
        lrep = _bj.pad_buckets_by_codes(
            rng.randint(0, card, l_starts[-1]), l_starts, card
        )
        rrep = _bj.pad_buckets_by_codes(
            rng.randint(0, card, r_starts[-1]), r_starts, card
        )
        if lrep is not None and rrep is not None:

            def sync_probe():
                lo, cnt = _bj.probe_code_ranges(lrep, rrep)
                np.asarray(cnt)

            saved_probe = os.environ.get("HYPERSPACE_PALLAS_PROBE")
            try:
                sync_probe()  # compile/warm whichever path auto picks
                packed_p50 = timed_p50(sync_probe, runs)
                os.environ["HYPERSPACE_PALLAS_PROBE"] = "0"  # force widen path
                sync_probe()
                widen_p50 = timed_p50(sync_probe, runs)
            finally:
                if saved_probe is None:
                    os.environ.pop("HYPERSPACE_PALLAS_PROBE", None)
                else:
                    os.environ["HYPERSPACE_PALLAS_PROBE"] = saved_probe
            out["probe"] = {
                "rows_l": int(l_starts[-1]),
                "rows_r": int(r_starts[-1]),
                "bits": lrep.bits,
                "probe_packed_p50_s": packed_p50,
                "probe_widen_p50_s": widen_p50,
                # Resident rep words vs the int8 flat matrix it replaces.
                "rep_bytes_packed": int(lrep.words.nbytes + rrep.words.nbytes),
                "rep_bytes_int8": int(
                    lrep.words.shape[0] * lrep.cap + rrep.words.shape[0] * rrep.cap
                ),
                "backend": jax.default_backend(),
            }
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    return {"packed_codes": out}


def _planner_section(s, base, col, runs, hs) -> dict:
    """Adaptive cost-based planner (`HYPERSPACE_PLANNER`): the acceptance
    bar is that a run with EVERY governed ambient flag unset (the planner
    deciding each knob per query) matches or beats the best hand-picked
    pinned configuration per workload:

    - ``planner_{agg,join}_p50_s``: the planner leg (all knobs unset);
    - ``planner_{agg,join}_best_pinned_p50_s``: min p50 across a pinned
      sweep (`HYPERSPACE_PLANNER=0` with defaults, streaming off, encoded
      off, and hash-quantize forced each way — the knobs whose wrong arm
      is the documented regression case);
    - ``planner_{agg,join}_vs_best_x``: planner over best-pinned (≈1.0 or
      below is the win condition; the standard noise bands apply);
    - ``planner_agg_arms``: the arms the model actually chose, for the
      artifact record.

    `tools/bench_compare.py --keys 'planner*'` gates these (self-gating:
    keys absent from both artifacts pass)."""
    from hyperspace_tpu.engine import io as _eio
    from hyperspace_tpu.engine.table import Table as _T
    from hyperspace_tpu.plananalysis import costmodel as _cm
    from hyperspace_tpu.plananalysis import planner as _planner

    n = int(os.environ.get("BENCH_PLANNER_ROWS", 400_000))
    n_dim = max(n // 8, 1000)
    fact_dir = os.path.join(base, "fact_planner")
    dim_dir = os.path.join(base, "dim_planner")
    rng = np.random.RandomState(53)
    keys = np.asarray([f"pk#{i:03d}" for i in range(64)])
    _eio.write_parquet(
        _T.from_pydict(
            {
                "k": keys[rng.randint(0, 64, n)].tolist(),
                "grp": keys[rng.randint(0, 16, n)].tolist(),
                "v": rng.randint(0, 1000, n).astype(np.int64).tolist(),
            }
        ),
        os.path.join(fact_dir, "part-00000.parquet"),
    )
    _eio.write_parquet(
        _T.from_pydict(
            {
                "k": keys[rng.randint(0, 64, n_dim)].tolist(),
                "w": rng.randint(0, 100, n_dim).astype(np.int64).tolist(),
            }
        ),
        os.path.join(dim_dir, "part-00000.parquet"),
    )

    def q_agg():
        return s.read.parquet(fact_dir).group_by("grp").agg(total=("v", "sum"))

    def q_join():
        return s.read.parquet(fact_dir).join(
            s.read.parquet(dim_dir), col("k") == col("k")
        )

    governed = list(_cm.KNOB_ENV.values()) + [_planner.ENV_PLANNER]
    saved = {k: os.environ.pop(k) for k in governed if k in os.environ}
    out: dict = {}
    try:
        _planner.reset()
        # Warm the scan caches + compiles once so every leg (planner first,
        # pinned sweep after) times the same steady state.
        q_agg().collect()
        q_join().collect()
        out["planner_agg_p50_s"] = round(timed_p50(lambda: q_agg().collect(), runs), 4)
        out["planner_join_p50_s"] = round(timed_p50(lambda: q_join().collect(), runs), 4)
        pd = _planner.decide(q_agg().physical_plan(), None)
        if pd is not None:
            out["planner_agg_arms"] = {k: d.arm for k, d in pd.decisions.items()}

        os.environ[_planner.ENV_PLANNER] = "0"
        pinned: dict = {}
        configs = {
            "defaults": {},
            "stream_off": {"HYPERSPACE_QUERY_STREAMING": "0"},
            "encoded_off": {"HYPERSPACE_ENCODED_EXEC": "0"},
            "quantize_on": {"HYPERSPACE_HASH_QUANTIZE": "1"},
            "quantize_off": {"HYPERSPACE_HASH_QUANTIZE": "0"},
        }
        for name, env in configs.items():
            for k_, v_ in env.items():
                os.environ[k_] = v_
            try:
                pinned[name] = {
                    "agg_p50_s": round(timed_p50(lambda: q_agg().collect(), runs), 4),
                    "join_p50_s": round(timed_p50(lambda: q_join().collect(), runs), 4),
                }
            finally:
                for k_ in env:
                    os.environ.pop(k_, None)
        out["planner_pinned"] = pinned
        best_agg = min(v["agg_p50_s"] for v in pinned.values())
        best_join = min(v["join_p50_s"] for v in pinned.values())
        out["planner_agg_best_pinned_p50_s"] = best_agg
        out["planner_join_best_pinned_p50_s"] = best_join
        if best_agg:
            out["planner_agg_vs_best_x"] = round(out["planner_agg_p50_s"] / best_agg, 3)
        if best_join:
            out["planner_join_vs_best_x"] = round(out["planner_join_p50_s"] / best_join, 3)
    finally:
        os.environ.pop(_planner.ENV_PLANNER, None)
        os.environ.update(saved)
    return out


def _attribution_section(s, base, col, runs, hs) -> dict:
    """Stage-level cost attribution: the acceptance experiment is that
    stage-grain learning corrects a mispriced knob that whole-wall learning
    cannot see. Setup: a bucket-join whose ``join_size_classes`` knob is
    given a mispriced model prior (model picks OFF; ON is measured-better
    inside the knob's own pad/probe/verify stages), plus an injected
    UNRELATED slowdown (an ``io.decode`` fault hang) that dominates the
    whole wall. Two legs over the same 8 queries:

    - ``attribution_stage_flip_query``: with ``HYPERSPACE_STAGE_ATTRIBUTION``
      on, the planner compares the knob's stage-local subtotals and flips to
      the measured-better arm (expected at query 5 with min_samples=2);
    - ``attribution_wall_flip_query``: with attribution off, the same
      mispricing hides inside the decode-dominated wall (expected 0 = never);
    - ``attribution_{stage,wall}_ratio``: alt/model means at each grain —
      the stage ratio clears the flip margin, the wall ratio does not;
    - ``attribution_overhead_{on,off}_p50_s``: the same warm join timed
      under both ambients with no faults or biases — the stage ledger's
      cost must be within the noise band in both directions.

    `tools/bench_compare.py --keys 'attribution*'` gates these."""
    from hyperspace_tpu import IndexConfig as _IndexConfig
    from hyperspace_tpu.engine import HyperspaceSession as _HS
    from hyperspace_tpu.engine import io as _eio
    from hyperspace_tpu.engine import physical as _phys
    from hyperspace_tpu.engine.scan_cache import (
        global_bucketed_cache as _gbc,
        global_concat_cache as _gcc,
        global_scan_cache as _gsc,
    )
    from hyperspace_tpu.engine.table import Table as _T
    from hyperspace_tpu.hyperspace import Hyperspace as _Hyperspace
    from hyperspace_tpu.hyperspace import enable_hyperspace as _enable
    from hyperspace_tpu.ops import bucket_join as _bj
    from hyperspace_tpu.plananalysis import costmodel as _cm
    from hyperspace_tpu.plananalysis import planner as _planner
    from hyperspace_tpu.telemetry import faults as _faults
    from hyperspace_tpu.telemetry import stage_ledger as _sl

    # Own session: the 16-bucket conf (few decode calls, so the injected
    # hang is a large CONSTANT per query) must not leak into later phases.
    sess = _HS(warehouse=base)
    sess.conf.set("hyperspace.index.num.buckets", "16")
    hs_local = _Hyperspace(sess)
    n, card = 60_000, 1000
    rng = np.random.RandomState(71)
    fact_dir = os.path.join(base, "fact_attr")
    dim_dir = os.path.join(base, "dim_attr")
    _eio.write_parquet(
        _T.from_pydict(
            {
                "fk": rng.randint(0, card, n).astype(np.int64).tolist(),
                "grp": rng.randint(0, 16, n).astype(np.int64).tolist(),
                "v": rng.randint(0, 1000, n).astype(np.int64).tolist(),
            }
        ),
        os.path.join(fact_dir, "part-00000.parquet"),
    )
    sess.write_parquet(
        {
            "k": np.arange(card, dtype=np.int64),
            "w": rng.randint(0, 100, card).astype(np.int64),
        },
        dim_dir,
    )
    # Both sides indexed with distinct join column names: the streamed
    # bucket-join path (JoinIndexRule applied) is the one whose pad/probe/
    # verify stages the knob governs.
    hs_local.create_index(
        sess.read.parquet(dim_dir), _IndexConfig("bench_attr_dim", ["k"], ["w"])
    )
    hs_local.create_index(
        sess.read.parquet(fact_dir),
        _IndexConfig("bench_attr_fact", ["fk"], ["grp", "v"]),
    )
    _enable(sess)

    def q():
        return (
            sess.read.parquet(fact_dir)
            .join(sess.read.parquet(dim_dir), col("fk") == col("k"))
            .group_by("grp")
            .agg(total=("v", "sum"))
        )

    def clear():
        _gsc().clear()
        _gcc().clear()
        _gbc().clear()
        _phys.clear_device_memos()

    governed = list(_cm.KNOB_ENV.values()) + [
        _planner.ENV_PLANNER,
        _planner.ENV_PLANNER_DIR,
        _planner.ENV_MIN_SAMPLES,
        _sl.ENV_STAGE_ATTRIBUTION,
    ]
    saved = {k: os.environ.pop(k) for k in governed if k in os.environ}
    real_estimate = _cm.estimate
    real_classed, real_ranges = _bj.probe_classed, _bj.probe_ranges
    out: dict = {}
    try:
        # Overhead first, with nothing monkeypatched: warm query, planner
        # off, attribution toggled by ambient only.
        os.environ[_planner.ENV_PLANNER] = "0"
        q().collect()
        for amb, key in (("1", "on"), ("0", "off")):
            os.environ[_sl.ENV_STAGE_ATTRIBUTION] = amb
            out[f"attribution_overhead_{key}_p50_s"] = round(
                timed_p50(lambda: q().collect(), runs), 4
            )

        # Mispriced prior: the model prices join_size_classes OFF as the
        # cheaper arm (it is not), and every other knob flat so only the
        # one flip is in play.
        def fixed_estimate(stats, cal, prune_selectivity=None):
            est = real_estimate(stats, cal)
            fx = {k: (mv, av, 0.0001, 0.0001) for k, (mv, av, _, _) in est.items()}
            fx["streaming"] = (True, False, 0.0001, 0.0001)
            fx["multiway"] = (False, True, 0.0001, 0.0001)
            fx["join_size_classes"] = (False, True, 0.006, 0.0065)
            return fx

        _cm.estimate = fixed_estimate

        # The knob's TRUE cost, made visible at stage grain: the classed
        # arm pays 2ms in its probe stage, the unclassed arm 20ms — tiny
        # against the ~1s decode-dominated wall, decisive against the
        # ~7ms stage subtotal. The guard keeps nested probe_ranges calls
        # (inside probe_classed) from double-billing.
        guard = threading.local()

        def biased(real):
            def inner(*a, **k):
                if getattr(guard, "on", False):
                    return real(*a, **k)
                guard.on = True
                try:
                    with _sl.stage_scope("probe"):
                        time.sleep(0.002 if _bj.size_classes_enabled() else 0.020)
                    return real(*a, **k)
                finally:
                    guard.on = False

            return inner

        _bj.probe_classed = biased(real_classed)
        _bj.probe_ranges = biased(real_ranges)

        # Warm both arms' compiles before any timed leg.
        for arm in ("1", "0"):
            os.environ["HYPERSPACE_JOIN_SIZE_CLASSES"] = arm
            clear()
            q().collect()
        os.environ.pop("HYPERSPACE_JOIN_SIZE_CLASSES", None)
        os.environ.pop(_planner.ENV_PLANNER, None)
        os.environ[_planner.ENV_MIN_SAMPLES] = "2"

        def run_leg(tag, attribution_on):
            os.environ[_planner.ENV_PLANNER_DIR] = os.path.join(
                base, f"planner_attr_{tag}"
            )
            os.environ[_sl.ENV_STAGE_ATTRIBUTION] = "1" if attribution_on else "0"
            _planner.reset()
            flip_at = 0
            with _faults.inject("io.decode", rate=1.0, kind="hang0.5"):
                for i in range(8):
                    clear()
                    q().collect()
                    act = _planner.activity_summary().get("join_size_classes", {})
                    if not flip_at and act.get("measured_flips"):
                        flip_at = i + 1
            arms = {
                key[2]: st
                for key, st in _planner.outcome_summary().items()
                if key[1] == "join_size_classes"
            }
            return flip_at, arms

        stage_flip, stage_arms = run_leg("stage", True)
        wall_flip, wall_arms = run_leg("wall", False)
        out["attribution_stage_flip_query"] = stage_flip
        out["attribution_wall_flip_query"] = wall_flip
        s_on = stage_arms.get("on", {}).get("mean_stage_s")
        s_off = stage_arms.get("off", {}).get("mean_stage_s")
        if s_on is not None and s_off:
            out["attribution_stage_on_mean_stage_s"] = s_on
            out["attribution_stage_off_mean_stage_s"] = s_off
            out["attribution_stage_ratio"] = round(s_on / s_off, 3)
        w_on = wall_arms.get("on", {}).get("mean_wall_s")
        w_off = wall_arms.get("off", {}).get("mean_wall_s")
        if w_on is not None and w_off:
            out["attribution_wall_on_mean_s"] = w_on
            out["attribution_wall_off_mean_s"] = w_off
            out["attribution_wall_ratio"] = round(w_on / w_off, 3)
    finally:
        _cm.estimate = real_estimate
        _bj.probe_classed = real_classed
        _bj.probe_ranges = real_ranges
        os.environ.pop("HYPERSPACE_JOIN_SIZE_CLASSES", None)
        for k in governed:
            os.environ.pop(k, None)
        os.environ.update(saved)
        _planner.reset()
        clear()
    return out


def _serving_section(s, base, col, runs, hs) -> dict:
    """Env-guard wrapper: the serving workload runs under serving-shaped
    chunk bounds and with hyperspace enabled — a mid-section failure (the
    cold-dedup asserts, a deadline) must not leak either into later phases
    (`_Phases.run` swallows section exceptions and keeps going)."""
    from hyperspace_tpu.hyperspace import disable_hyperspace

    chunk_env = (
        "HYPERSPACE_JOIN_CHUNK_ROWS",
        "HYPERSPACE_QUERY_CHUNK_ROWS",
        # The serving section runs under the workload-history posture
        # (HYPERSPACE_HISTORY=1): every served query's ledger lands in the
        # on-lake store, and bench_detail.serving reports the store + SLO
        # summaries — the ambient default stays off for the headline
        # sections (the zero-cost-off contract is ALSO a bench claim).
        "HYPERSPACE_HISTORY",
        "HYPERSPACE_HISTORY_DIR",
    )
    saved = {k: os.environ.get(k) for k in chunk_env}
    try:
        return _serving_section_body(s, base, col, runs, hs)
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        disable_hyperspace(s)


def _serving_section_body(s, base, col, runs, hs) -> dict:
    """Sustained multi-tenant traffic through `serve.QueryServer`
    (docs/serving.md): N client threads × a mixed Q1/Q3/Q14/point-lookup
    workload against the already-built indexes.

    Reported per query class: the SERIAL warm p50 (one caller, no server)
    and the CONCURRENT p50/p99 as experienced by the clients (submit →
    result, queue wait included) — plus total throughput, the single-flight
    dedup counters, and a cold-scan dedup probe (two identical concurrent
    cold scans must decode the lake once: the acceptance counter-assert).
    ``point_p99_x_serial_p50`` is the headline tail metric: the priority
    lane + reserved interactive worker keep point lookups from queueing
    behind cold scans."""
    import threading

    from hyperspace_tpu import IndexConfig
    from hyperspace_tpu.engine import io as _eio
    from hyperspace_tpu.engine.scan_cache import (
        global_concat_cache,
        global_scan_cache,
    )
    from hyperspace_tpu.hyperspace import enable_hyperspace
    from hyperspace_tpu.serve import QueryServer
    from hyperspace_tpu.telemetry import metrics

    clients = int(os.environ.get("BENCH_SERVE_CLIENTS", 8))
    per_client = int(os.environ.get("BENCH_SERVE_QUERIES", 10))
    # 3 workers (1 reserved interactive + 2 batch) is the 1-core sweet spot:
    # batch throughput is CPU-bound either way (measured ~66 qps at 3 AND 4
    # workers), while each extra concurrent batch query adds its GIL-held
    # op tails to every point lookup's p99.
    workers = int(os.environ.get("BENCH_SERVE_MAX_CONCURRENT", 3))
    # Serving-shaped chunk bound: a latency SLO wants short batch work
    # quanta — smaller streamed/join chunks bound every GIL-held numpy op
    # AND put a cooperative yield boundary every few milliseconds
    # (docs/serving.md). Applied to the WHOLE section (serial baselines
    # included) so the comparison is apples-to-apples; the `_serving_section`
    # wrapper restores the env whatever happens below.
    chunk_rows = str(int(os.environ.get("BENCH_SERVE_CHUNK_ROWS", 65536)))
    for k in ("HYPERSPACE_JOIN_CHUNK_ROWS", "HYPERSPACE_QUERY_CHUNK_ROWS"):
        os.environ[k] = chunk_rows
    # Workload-history posture for the serving mix (docs/observability.md):
    # served ledgers land on the lake, keyed by plan fingerprint; the
    # summaries ride bench_detail.serving below.
    from hyperspace_tpu.telemetry import history as _tel_history
    from hyperspace_tpu.telemetry import slo as _tel_slo

    # BENCH_HISTORY_DIR preserves the store past the bench's temp-dir
    # cleanup (so `tools/hsreport.py <dir>` renders the run afterwards);
    # default keeps it inside the section's temp base.
    hist_dir = os.environ.get("BENCH_HISTORY_DIR") or os.path.join(
        base, "serve_history"
    )
    os.environ["HYPERSPACE_HISTORY"] = "1"
    os.environ["HYPERSPACE_HISTORY_DIR"] = hist_dir
    _tel_slo.reset()
    from hyperspace_tpu.telemetry import metrics as _tel_metrics

    anomalies0 = _tel_metrics.counter("history.anomalies").value
    # The section owns its dataset (like pushdown/encoded): the serving story
    # is scheduling + sharing, measured at a serving-shaped scale regardless
    # of the headline BENCH_LINEITEM_ROWS.
    n = int(os.environ.get("BENCH_SERVE_ROWS", 500_000))
    n_ord, n_part = max(n // 8, 1000), max(n // 20, 500)
    rng = np.random.RandomState(7)
    sv_dir = os.path.join(base, "serve")
    _write_chunked(
        {
            "orderkey": rng.randint(0, n_ord, n).astype(np.int64),
            "partkey": rng.randint(0, n_part, n).astype(np.int64),
            "qty": rng.randint(1, 51, n).astype(np.int64),
            "price": (rng.rand(n) * 1000).astype(np.float64),
            "discount": (rng.randint(0, 11, n) / 100.0),
            "shipdate": rng.randint(0, 2526, n).astype(np.int64),
        },
        os.path.join(sv_dir, "lineitem"),
        16,
    )
    _write_chunked(
        {
            "o_orderkey": np.arange(n_ord, dtype=np.int64),
            "o_custkey": rng.randint(0, max(n_ord // 25, 100), n_ord).astype(np.int64),
        },
        os.path.join(sv_dir, "orders"),
        4,
    )
    types = np.array(
        [f"{'PROMO' if i % 5 == 0 else 'STD'} TYPE#{i:02d}" for i in range(25)]
    )
    _write_chunked(
        {
            "p_partkey": np.arange(n_part, dtype=np.int64),
            "p_type": types[np.arange(n_part) % 25],
        },
        os.path.join(sv_dir, "part"),
        2,
    )
    li = lambda: s.read.parquet(os.path.join(sv_dir, "lineitem"))
    orders = lambda: s.read.parquet(os.path.join(sv_dir, "orders"))
    part = lambda: s.read.parquet(os.path.join(sv_dir, "part"))
    hs.create_index(li(), IndexConfig("srvLiIdx", ["orderkey"], ["qty", "price"]))
    hs.create_index(orders(), IndexConfig("srvOrdIdx", ["o_orderkey"], ["o_custkey"]))
    hs.create_index(part(), IndexConfig("srvPartIdx", ["p_partkey"], ["p_type"]))
    hs.create_index(
        li(), IndexConfig("srvLiPartIdx", ["partkey"], ["price", "discount", "shipdate"])
    )
    enable_hyperspace(s)

    def q1():
        return (
            li()
            .group_by("discount")
            .agg(sum_qty=("qty", "sum"), sum_price=("price", "sum"), n=("qty", "count"))
            .collect()
        )

    def q3():
        return (
            li()
            .join(orders(), col("orderkey") == col("o_orderkey"))
            .with_column("revenue", col("price") * (1 - col("discount")))
            .group_by("o_custkey")
            .agg(revenue=("revenue", "sum"), n=("qty", "count"))
            .order_by(("revenue", False))
            .limit(10)
            .collect()
        )

    def q14():
        return (
            li()
            .filter((col("shipdate") >= 1000) & (col("shipdate") < 1030))
            .join(part(), col("partkey") == col("p_partkey"))
            .with_column("revenue", col("price") * (1 - col("discount")))
            .group_by("p_type")
            .agg(revenue=("revenue", "sum"))
            .order_by(("revenue", False))
            .limit(5)
            .collect()
        )

    point_keys = [n_ord // 2 + 3 * i for i in range(16)]

    def q_point(key=None):
        # Indexed point lookup (srvLiIdx bucket pruning): the interactive
        # class. Rotating literals so the filtered cache isn't the whole
        # story — each key is its own filtered-concat entry.
        key = point_keys[0] if key is None else key
        return li().filter(col("orderkey") == key).select("qty", "price").collect()

    batch_classes = [("q1_agg", q1), ("q3_join", q3), ("q14", q14)]
    out = {
        "clients": clients,
        "queries_per_client": per_client,
        "max_concurrent": workers,
        "rows": n,
    }

    # -- serial warm baselines, measured through an IDLE server (the same
    #    submit→result instrumentation path the concurrent numbers ride) ----
    srv = QueryServer(max_concurrent=workers)
    try:
        serial = {}
        for name, q in batch_classes:
            q()  # warm
            serial[name] = round(timed_p50(lambda q=q: srv.run(q, lane="batch"), runs), 4)
        for key in point_keys:
            q_point(key)  # warm each rotating literal
        serial["point"] = round(
            timed_p50(
                lambda: srv.run(lambda: q_point(point_keys[0]), lane="interactive"),
                max(runs, 5),
            ),
            4,
        )
        out["serial_p50_s"] = serial

        # -- sustained concurrent mixed run (half the traffic is point lookups:
        #    the serving-shaped mix the tail metric is about) -------------------
        snap0 = metrics.snapshot()["counters"]
        latencies = {name: [] for name, _q in batch_classes}
        latencies["point"] = []
        errors = []

        def client(ci: int):
            for j in range(per_client):
                if j % 2 == 1:
                    name, lane = "point", "interactive"
                    key = point_keys[(ci * per_client + j) % len(point_keys)]
                    q = lambda key=key: q_point(key)
                else:
                    name, q = batch_classes[(ci + j // 2) % len(batch_classes)]
                    lane = "batch"
                t0 = _now()
                try:
                    srv.run(q, tenant=f"client{ci % 4}", lane=lane)
                except Exception as e:  # admission rejections count as errors here
                    errors.append(f"{name}: {type(e).__name__}")
                    continue
                latencies[name].append(_now() - t0)

        t_start = _now()
        threads = [threading.Thread(target=client, args=(ci,)) for ci in range(clients)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = _now() - t_start
    finally:
        # The server's workers must die with the section: a mid-phase
        # failure (the dedup asserts, a deadline) leaving live workers
        # would corrupt every later bench phase's measurements.
        srv.close()
    snap1 = metrics.snapshot()["counters"]
    total = sum(len(v) for v in latencies.values())
    out["wall_s"] = round(wall, 3)
    out["throughput_qps"] = round(total / wall, 2) if wall > 0 else None
    out["errors"] = errors
    per_class = {}
    for name, vals in latencies.items():
        if not vals:
            continue
        arr = np.sort(np.asarray(vals))
        per_class[name] = {
            "n": len(vals),
            "p50_s": round(float(np.percentile(arr, 50)), 4),
            "p99_s": round(float(np.percentile(arr, 99)), 4),
            "max_s": round(float(arr[-1]), 4),
        }
    out["concurrent"] = per_class
    if "point" in per_class and serial.get("point"):
        out["point_p99_x_serial_p50"] = round(
            per_class["point"]["p99_s"] / max(serial["point"], 1e-9), 2
        )
    out["counters"] = {
        k: snap1.get(k, 0) - snap0.get(k, 0)
        for k in (
            "serve.admitted",
            "serve.completed",
            "serve.failed",
            "serve.singleflight.leaders",
            "serve.singleflight.dedup_hits",
            "serve.singleflight.follower_retries",
            "io.decode.files",
        )
    }

    # -- cold-scan dedup probe: the acceptance counter-assert ---------------
    global_scan_cache().clear()
    global_concat_cache().clear()
    n_files = len(
        [
            f
            for f in os.listdir(os.path.join(sv_dir, "orders"))
            if f.endswith(".parquet")
        ]
    )
    snap0 = metrics.snapshot()["counters"]
    barrier = threading.Barrier(2)
    cold_times = []

    def cold_scan():
        barrier.wait(60)
        t0 = _now()
        orders().collect()
        cold_times.append(_now() - t0)

    with QueryServer(max_concurrent=3) as srv2:
        f1 = srv2.submit(cold_scan, tenant="cold_a")
        f2 = srv2.submit(cold_scan, tenant="cold_b")
        f1.result(300), f2.result(300)
    snap1 = metrics.snapshot()["counters"]
    decode_delta = snap1.get("io.decode.files", 0) - snap0.get("io.decode.files", 0)
    dedup_delta = snap1.get("serve.singleflight.dedup_hits", 0) - snap0.get(
        "serve.singleflight.dedup_hits", 0
    )
    assert decode_delta == n_files, (decode_delta, n_files)
    assert dedup_delta >= 1, dedup_delta
    out["cold_dedup"] = {
        "files": n_files,
        "decodes": decode_delta,
        "dedup_hits": dedup_delta,
        "scan_s": [round(t, 3) for t in sorted(cold_times)],
    }

    # -- workload history + SLO over the section's traffic ------------------
    # Every served query above landed its ledger in the on-lake store; the
    # per-lane SLO monitor watched the same submissions. Both summaries ride
    # the bench artifact so regression gates and operators read one file.
    out["slo"] = _tel_slo.summary()
    hist_recs = [
        r
        for r in _tel_history.iter_records(hist_dir)
        if r.get("kind") == "ledger"
    ]
    baselines = _tel_history.fold_baselines(iter(hist_recs))
    top = sorted(
        baselines.items(), key=lambda kv: -(kv[1].summary().get("wall_total_s") or 0)
    )
    out["history"] = {
        "records": len(hist_recs),
        "fingerprints": len(baselines),
        "segments": len(
            [f for f in os.listdir(hist_dir) if f.endswith(".jsonl")]
        )
        if os.path.isdir(hist_dir)
        else 0,
        # Section DELTA (same convention as the `counters` block above): an
        # ambient-history run's earlier anomalies must not be attributed to
        # the serving mix.
        "anomalies": metrics.counter("history.anomalies").value - anomalies0,
        "top_classes": {
            fp: bl.summary() for fp, bl in top[:3]
        },
    }
    return {"serving": out}


def _live_tables_section(s, base, col, runs, hs) -> dict:
    """Live tables under the serving mix (docs/reliability.md "Live tables"):
    append batches LAND while interactive point lookups run; each batch is
    folded in by an incremental refresh and the accumulated delta files are
    coalesced by a background compaction — both as BATCH-lane citizens on the
    serving scheduler, so the headline is the interactive tail DURING
    refresh/compaction vs idle.

    Reported: per-batch staleness at landing + freshness lag (append →
    refresh committed), refresh/compact latency, interactive p50/p99 idle /
    during-refresh / during-compaction / after, and the delta-file counts
    that prove the layout churn. ``point_p99_during_refresh_x_idle`` is the
    acceptance ratio (target ≤ 3)."""
    import threading

    from hyperspace_tpu import IndexConfig, IndexConstants
    from hyperspace_tpu.actions.optimize import needs_compaction
    from hyperspace_tpu.engine import io as _eio
    from hyperspace_tpu.engine.table import Table
    from hyperspace_tpu.hyperspace import disable_hyperspace, enable_hyperspace
    from hyperspace_tpu.serve import QueryServer
    from hyperspace_tpu.telemetry import metrics

    if os.environ.get("BENCH_SKIP_LIVE") == "1":
        return {}
    n = int(os.environ.get("BENCH_LIVE_ROWS", 200_000))
    batches = int(os.environ.get("BENCH_LIVE_BATCHES", 3))
    batch_rows = int(os.environ.get("BENCH_LIVE_BATCH_ROWS", max(n // 10, 1000)))
    workers = int(os.environ.get("BENCH_SERVE_MAX_CONCURRENT", 3))
    chunk_env = ("HYPERSPACE_JOIN_CHUNK_ROWS", "HYPERSPACE_QUERY_CHUNK_ROWS")
    saved_env = {k: os.environ.get(k) for k in chunk_env}
    saved_conf = {
        k: s.conf.get(k)
        for k in (
            IndexConstants.INDEX_LINEAGE_ENABLED,
            IndexConstants.INDEX_HYBRID_SCAN_ENABLED,
            IndexConstants.INDEX_NUM_BUCKETS,
        )
    }
    out = {"rows": n, "batches": batches, "batch_rows": batch_rows}
    try:
        # Serving SLO posture (same as the serving section): short batch work
        # quanta = frequent cooperative yield boundaries for the batch-lane
        # refresh/compaction to pause at.
        chunk_rows = str(int(os.environ.get("BENCH_SERVE_CHUNK_ROWS", 65536)))
        for k in chunk_env:
            os.environ[k] = chunk_rows
        # Lineage ON: the delete-folding path is part of the live contract.
        # Hybrid scan ON: between an append landing and its refresh
        # committing, queries serve FRESH rows by merging the appended files
        # at scan time — the graceful-degradation half of the story.
        s.conf.set(IndexConstants.INDEX_LINEAGE_ENABLED, "true")
        s.conf.set(IndexConstants.INDEX_HYBRID_SCAN_ENABLED, "true")
        # The live index owns its bucket count: per-refresh delta cost scales
        # with it (one delta file per non-empty bucket per refresh).
        s.conf.set(
            IndexConstants.INDEX_NUM_BUCKETS,
            int(os.environ.get("BENCH_LIVE_BUCKETS", 16)),
        )

        rng = np.random.RandomState(11)
        lv_dir = os.path.join(base, "live")
        tbl = os.path.join(lv_dir, "events")
        n_keys = max(n // 8, 1000)
        _write_chunked(
            {
                "ek": rng.randint(0, n_keys, n).astype(np.int64),
                "qty": rng.randint(1, 51, n).astype(np.int64),
                "price": (rng.rand(n) * 1000).astype(np.float64),
            },
            tbl,
            8,
        )
        ev = lambda: s.read.parquet(tbl)  # noqa: E731
        t0 = _now()
        hs.create_index(ev(), IndexConfig("liveEvIdx", ["ek"], ["qty", "price"]))
        out["build_s"] = round(_now() - t0, 3)
        enable_hyperspace(s)

        point_keys = [n_keys // 2 + 7 * i for i in range(16)]

        def q_point(key):
            return ev().filter(col("ek") == key).select("qty", "price").collect()

        def measure_points(srv, n_samples):
            vals = []
            for i in range(n_samples):
                key = point_keys[i % len(point_keys)]
                t0 = _now()
                srv.run(lambda key=key: q_point(key), lane="interactive")
                vals.append(_now() - t0)
            return vals

        def pstats(vals):
            arr = np.sort(np.asarray(vals))
            return {
                "n": len(vals),
                "p50_s": round(float(np.percentile(arr, 50)), 4),
                "p99_s": round(float(np.percentile(arr, 99)), 4),
            }

        refresh_walls = []  # SECTION-local samples (the lifetime histogram
        # may carry foreign refreshes — the PR-11 section-DELTA convention)

        def timed_refresh():
            t0 = _now()
            hs.refresh_index("liveEvIdx", mode="incremental")
            refresh_walls.append(_now() - t0)

        srv = QueryServer(max_concurrent=workers)
        try:
            for key in point_keys:
                q_point(key)  # warm each rotating literal
            out["point_idle"] = pstats(measure_points(srv, max(4 * runs, 24)))

            # -- streaming ingest: batches land, refreshes fold them in while
            #    the interactive mix keeps running ---------------------------
            staleness, freshness_lag, during_refresh = [], [], []
            next_key = n
            for b in range(batches):
                _eio.write_parquet(
                    Table.from_pydict(
                        {
                            "ek": rng.randint(0, n_keys, batch_rows).astype(np.int64),
                            "qty": rng.randint(1, 51, batch_rows).astype(np.int64),
                            "price": (rng.rand(batch_rows) * 1000).astype(np.float64),
                        }
                    ),
                    os.path.join(tbl, f"append-{b:05d}.parquet"),
                )
                landed = _now()
                # One query between landing and refresh: hybrid scan serves
                # the fresh rows and the candidate diff publishes staleness.
                q_point(point_keys[b % len(point_keys)])
                staleness.append(
                    metrics.gauge("index.staleness_s.liveEvIdx").value
                )
                fut = srv.submit(timed_refresh, lane="batch")
                # The interactive mix DURING the refresh.
                while not fut.done():
                    during_refresh.extend(measure_points(srv, 4))
                fut.result(600)
                freshness_lag.append(round(_now() - landed, 3))
            out["staleness_at_landing_s"] = staleness
            out["freshness_lag_s"] = freshness_lag
            if not during_refresh:
                # Inline-serial serving (HYPERSPACE_SERVING=0) or a refresh
                # faster than one probe round: measure right after instead of
                # reporting an empty window.
                during_refresh = measure_points(srv, 8)
            out["point_during_refresh"] = pstats(during_refresh)
            out["refresh_count"] = len(refresh_walls)
            out["refresh_latency_p50_s"] = round(
                float(np.percentile(np.asarray(refresh_walls), 50)), 3
            )

            # -- background compaction under the same mix -------------------
            entry = [e for e in hs._manager.get_indexes() if e.name == "liveEvIdx"][0]
            out["delta_files_before_compact"] = len(entry.content.files())
            out["needs_compaction"] = needs_compaction(entry)
            during_compact = []
            t0 = _now()
            fut = srv.submit(lambda: hs.optimize_index("liveEvIdx"), lane="batch")
            while not fut.done():
                during_compact.extend(measure_points(srv, 4))
            fut.result(600)
            out["compact_s"] = round(_now() - t0, 3)
            if during_compact:
                out["point_during_compact"] = pstats(during_compact)
            entry = [e for e in hs._manager.get_indexes() if e.name == "liveEvIdx"][0]
            out["files_after_compact"] = len(entry.content.files())

            for key in point_keys:
                q_point(key)  # re-warm: compaction is a new generation
            out["point_after"] = pstats(measure_points(srv, max(4 * runs, 24)))
        finally:
            srv.close()
        idle_p99 = max(out["point_idle"]["p99_s"], 1e-9)
        out["point_p99_during_refresh_x_idle"] = round(
            out["point_during_refresh"]["p99_s"] / idle_p99, 2
        )
        if "point_during_compact" in out:
            out["point_p99_during_compact_x_idle"] = round(
                out["point_during_compact"]["p99_s"] / idle_p99, 2
            )
        return {"live_tables": out}
    finally:
        for k, v in saved_env.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        for k, v in saved_conf.items():
            if v is None:
                s.conf.unset(k)
            else:
                s.conf.set(k, v)
        disable_hyperspace(s)


def _stable_table_hash(t) -> str:
    """Order-insensitive content hash of a collected Table: column names +
    sorted row tuples. Used for the replica-fleet byte-identity asserts —
    every replica (and the HYPERSPACE_REPLICAS=0 fallback) must produce the
    same hash for the same query."""
    import hashlib

    return hashlib.sha256(
        repr((t.column_names, t.sorted_rows())).encode()
    ).hexdigest()


def _replica_section(s, base, col, runs, hs) -> dict:
    """Env-guard wrapper for the replica-fleet section: the fallback probe and
    the child launches must not leak HYPERSPACE_REPLICAS / registry / history
    env into later phases, whatever happens mid-section."""
    from hyperspace_tpu.hyperspace import disable_hyperspace

    if os.environ.get("BENCH_SKIP_REPLICAS") == "1":
        return {"replicas": {"skipped": True}}
    saved = {
        k: os.environ.get(k)
        for k in (
            "HYPERSPACE_REPLICAS",
            "HYPERSPACE_REPLICA_DIR",
            "HYPERSPACE_HISTORY",
            "HYPERSPACE_HISTORY_DIR",
        )
    }
    # The parent is the HYPERSPACE_REPLICAS=0 oracle: fleet machinery must be
    # fully off in-process while the children run with it on.
    os.environ.pop("HYPERSPACE_REPLICAS", None)
    os.environ.pop("HYPERSPACE_REPLICA_DIR", None)
    try:
        return _replica_section_body(s, base, col, runs, hs)
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        disable_hyperspace(s)


def _replica_section_body(s, base, col, runs, hs) -> dict:
    """Scale-out replica serving (docs/serving.md "Replica fleet"): K replica
    subprocesses × the serving client mix against ONE shared lake.

    Per K in {1,2,3}: launch K `bench.py` children (BENCH_CHILD=replica) that
    join the on-lake registry over a shared registry dir, barrier on
    live==K, partition the point-lookup keyset by rendezvous ownership of
    each key's index bucket file (the SAME routing key `engine/io.py` uses),
    run the cold point phase + a fixed fleet-wide mixed workload, and report
    per-child decode counters, walls, and result hashes.

    Headline numbers:
      - aggregate qps at K vs K=1 (same fleet-wide workload, so scaling is
        real parallelism — gated on >=3 usable cores: on a 1-core container
        multi-process CPU-bound scaling is physically impossible and the
        assert would only measure the scheduler);
      - cross-replica cold-decode dedup: summed io.decode.files across the
        fleet equals the DISTINCT bucket-file count (what the fallback
        single process decodes), not K× it;
      - byte-identity: every per-key and aggregate result hash equals the
        parent's HYPERSPACE_REPLICAS=0 fallback hash."""
    import glob as _glob
    import subprocess

    from hyperspace_tpu import IndexConfig
    from hyperspace_tpu.engine.schema import INT64
    from hyperspace_tpu.engine.scan_cache import (
        global_concat_cache,
        global_scan_cache,
    )
    from hyperspace_tpu.hyperspace import enable_hyperspace
    from hyperspace_tpu.rules.filter_index_rule import _bucket_of_literal
    from hyperspace_tpu.telemetry import metrics

    n = int(os.environ.get("BENCH_REPLICA_ROWS", 120_000))
    n_files = 8
    n_keys = int(os.environ.get("BENCH_REPLICA_KEYS", 16))
    workload = int(os.environ.get("BENCH_REPLICA_WORKLOAD", 48))
    max_k = int(os.environ.get("BENCH_REPLICA_MAX_K", 3))
    n_ord = max(n // 8, 1000)
    rng = np.random.RandomState(11)
    rp_dir = os.path.join(base, "replicas")
    _write_chunked(
        {
            "orderkey": rng.randint(0, n_ord, n).astype(np.int64),
            "qty": rng.randint(1, 51, n).astype(np.int64),
            "price": (rng.rand(n) * 1000).astype(np.float64),
            "discount": (rng.randint(0, 11, n) / 100.0),
        },
        os.path.join(rp_dir, "lineitem"),
        n_files,
    )
    li = lambda: s.read.parquet(os.path.join(rp_dir, "lineitem"))
    hs.create_index(li(), IndexConfig("repLiIdx", ["orderkey"], ["qty", "price"]))
    enable_hyperspace(s)

    point_keys = [n_ord // 2 + 3 * i for i in range(n_keys)]

    def q_point(key):
        return li().filter(col("orderkey") == key).select("qty", "price").collect()

    def q_agg():
        return (
            li()
            .group_by("discount")
            .agg(sum_qty=("qty", "sum"), sum_price=("price", "sum"), n=("qty", "count"))
            .collect()
        )

    # Map every point key to its index bucket part file — the exact path
    # string `engine/io.py` routes decodes by, so the children's key
    # partition and the runtime's ownership routing can never disagree.
    from hyperspace_tpu.config import IndexConstants

    num_buckets = s.conf.get_int(
        IndexConstants.INDEX_NUM_BUCKETS, IndexConstants.INDEX_NUM_BUCKETS_DEFAULT
    )
    idx_root = os.path.join(base, "indexes", "repLiIdx")
    bucket2path = {}
    for p in sorted(
        _glob.glob(os.path.join(idx_root, "**", "part-*.parquet"), recursive=True)
    ):
        b = int(os.path.basename(p)[len("part-") : -len(".parquet")])
        bucket2path[b] = p  # later (higher) versions win the sort
    key_paths = {}
    for k in point_keys:
        b = _bucket_of_literal(k, INT64, num_buckets)
        assert b is not None and b in bucket2path, (k, b)
        key_paths[str(k)] = bucket2path[b]
    distinct_files = len(set(key_paths.values()))

    out = {
        "rows": n,
        "point_keys": n_keys,
        "workload": workload,
        "distinct_bucket_files": distinct_files,
    }

    # -- HYPERSPACE_REPLICAS=0 oracle (cold): the byte-identity + dedup
    #    baseline every fleet run is compared against -----------------------
    global_scan_cache().clear()
    global_concat_cache().clear()
    snap0 = metrics.snapshot()["counters"]
    t0 = _now()
    oracle_hashes = {str(k): _stable_table_hash(q_point(k)) for k in point_keys}
    fallback_wall = _now() - t0
    snap1 = metrics.snapshot()["counters"]
    fallback_decodes = snap1.get("io.decode.files", 0) - snap0.get(
        "io.decode.files", 0
    )
    oracle_agg = _stable_table_hash(q_agg())
    assert fallback_decodes == distinct_files, (fallback_decodes, distinct_files)
    out["fallback"] = {
        "cold_decode_files": fallback_decodes,
        "point_wall_s": round(fallback_wall, 3),
    }

    # -- K-replica fleet runs ----------------------------------------------
    timeout_s = int(os.environ.get("BENCH_REPLICA_TIMEOUT_S", 300))
    by_k = {}
    for k_replicas in range(1, max_k + 1):
        reg = os.path.join(rp_dir, f"reg_k{k_replicas}")
        hist = os.path.join(rp_dir, f"history_k{k_replicas}")
        os.makedirs(reg, exist_ok=True)
        procs = []
        for ci in range(k_replicas):
            conf_path = os.path.join(rp_dir, f"conf_k{k_replicas}_c{ci}.json")
            out_path = os.path.join(rp_dir, f"out_k{k_replicas}_c{ci}.json")
            with open(conf_path, "w") as f:
                json.dump(
                    {
                        "warehouse": base,
                        "data_dir": os.path.join(rp_dir, "lineitem"),
                        "k": k_replicas,
                        "child_index": ci,
                        "point_keys": point_keys,
                        "key_paths": key_paths,
                        "workload": workload,
                        "out_path": out_path,
                    },
                    f,
                )
            env = dict(os.environ)
            env[_CHILD_ENV] = "replica"
            env["BENCH_REPLICA_CONF"] = conf_path
            env["JAX_PLATFORMS"] = "cpu"
            env.pop("XLA_FLAGS", None)
            env["HYPERSPACE_REPLICAS"] = "1"
            env["HYPERSPACE_REPLICA_DIR"] = reg
            env["HYPERSPACE_HISTORY"] = "1"
            env["HYPERSPACE_HISTORY_DIR"] = hist
            procs.append(
                (
                    out_path,
                    subprocess.Popen(
                        [sys.executable, os.path.abspath(__file__)],
                        env=env,
                        stdout=subprocess.PIPE,
                        stderr=subprocess.PIPE,
                        text=True,
                    ),
                )
            )
        results = []
        for out_path, p in procs:
            try:
                _, err = p.communicate(timeout=timeout_s)
            except subprocess.TimeoutExpired:
                p.kill()
                p.communicate()
                raise AssertionError(f"replica child timeout at K={k_replicas}")
            assert p.returncode == 0, (
                f"replica child rc={p.returncode} at K={k_replicas}: "
                f"{err.strip()[-400:]}"
            )
            with open(out_path) as f:
                results.append(json.load(f))

        # Fleet-wide aggregates + the per-K invariants.
        covered = sorted(k for r in results for k in r["owned_keys"])
        assert covered == sorted(str(k) for k in point_keys), covered
        for r in results:
            for key, h in r["hashes"].items():
                assert h == oracle_hashes[key], (k_replicas, key)
            assert r["agg_hash"] == oracle_agg, (k_replicas, r["replica_id"])
        cold_decodes = sum(r["cold"]["decode_files"] for r in results)
        completed = sum(r["mix"]["completed"] for r in results)
        wall = max(r["mix"]["wall_s"] for r in results)
        by_k[f"k{k_replicas}"] = {
            "replicas": k_replicas,
            "fleet_cold_decode_files": cold_decodes,
            "completed": completed,
            "wall_s": round(wall, 3),
            "qps": round(completed / wall, 2) if wall > 0 else None,
            "errors": sum(r["mix"]["errors"] for r in results),
            "live_seen": [r["live_seen"] for r in results],
        }
        # Cross-replica cold-decode dedup: the fleet decodes each distinct
        # bucket file ONCE total (what the single-process fallback pays),
        # not once per replica.
        assert cold_decodes == fallback_decodes, (
            k_replicas,
            cold_decodes,
            fallback_decodes,
        )
    out.update(by_k)

    # -- qps scaling headline ----------------------------------------------
    q1, qK = by_k["k1"]["qps"], by_k[f"k{max_k}"]["qps"]
    if q1 and qK:
        out["scaling_vs_k1"] = round(qK / q1, 2)
    cores = len(os.sched_getaffinity(0)) if hasattr(os, "sched_getaffinity") else (
        os.cpu_count() or 1
    )
    out["cores"] = cores
    min_scaling = float(os.environ.get("BENCH_REPLICA_MIN_SCALING", 1.8))
    if cores >= max_k:
        assert out.get("scaling_vs_k1", 0) >= min_scaling, (
            out.get("scaling_vs_k1"),
            min_scaling,
        )
    else:
        # On a 1-core container K CPU-bound processes timeshare one core:
        # aggregate qps is physically flat however good the coordination is.
        # The dedup + byte-identity asserts above still ran at full strength.
        out["scaling_gated"] = f"insufficient_cores({cores}<{max_k})"
    return {"replicas": out}


def _replica_child_main() -> None:
    """One replica of the bench fleet (BENCH_CHILD=replica): join the on-lake
    registry, barrier on live==K, serve the owned slice of the point keyset
    cold, then the child's share of the fleet-wide mixed workload. Emits a
    JSON result file; never prints to stdout (engine warnings aside)."""
    import time as _time

    conf = json.load(open(os.environ["BENCH_REPLICA_CONF"]))
    from hyperspace_tpu import Hyperspace, HyperspaceSession
    from hyperspace_tpu.engine import col as _col
    from hyperspace_tpu.hyperspace import enable_hyperspace
    from hyperspace_tpu.serve import QueryServer
    from hyperspace_tpu.serve import replicas as _replicas
    from hyperspace_tpu.telemetry import metrics

    s = HyperspaceSession(warehouse=conf["warehouse"])
    Hyperspace(s)
    enable_hyperspace(s)
    li = lambda: s.read.parquet(conf["data_dir"])

    def q_point(key):
        return li().filter(_col("orderkey") == key).select("qty", "price").collect()

    def q_agg():
        return (
            li()
            .group_by("discount")
            .agg(sum_qty=("qty", "sum"), sum_price=("price", "sum"), n=("qty", "count"))
            .collect()
        )

    result = {"replica_id": None, "owned_keys": [], "hashes": {}}
    srv = QueryServer(max_concurrent=2)  # joins the fleet (HYPERSPACE_REPLICAS=1)
    try:
        rid = _replicas.replica_id()
        result["replica_id"] = rid
        # Barrier: wait for the whole fleet before partitioning ownership,
        # so every child computes the same rendezvous view.
        deadline = _time.time() + 60
        while len(_replicas.live_replicas(refresh=True)) < conf["k"]:
            if _time.time() > deadline:
                raise RuntimeError(
                    f"fleet barrier timeout: live="
                    f"{len(_replicas.live_replicas(refresh=True))} want={conf['k']}"
                )
            _time.sleep(0.05)
        result["live_seen"] = len(_replicas.live_replicas())

        # Partition point keys by rendezvous ownership of each key's bucket
        # part file — the same key string engine/io.py routes by.
        owned = [
            k
            for k, path in conf["key_paths"].items()
            if _replicas.owner_of(path) == rid
        ]
        result["owned_keys"] = owned

        # -- cold point phase: only owned keys → each bucket file decoded by
        #    exactly one replica fleet-wide ---------------------------------
        snap0 = metrics.snapshot()["counters"]
        t0 = _now()
        for key in owned:
            t = srv.run(
                lambda key=int(key): q_point(key),
                tenant=f"replica{conf['child_index']}",
                lane="interactive",
            )
            result["hashes"][key] = _stable_table_hash(t)
        snap1 = metrics.snapshot()["counters"]
        result["cold"] = {
            "decode_files": snap1.get("io.decode.files", 0)
            - snap0.get("io.decode.files", 0),
            "wall_s": round(_now() - t0, 3),
        }

        # -- fleet-wide mixed workload, sharded by slot index ---------------
        keys = conf["point_keys"]
        errors = 0
        completed = 0
        agg_hash = None
        t0 = _now()
        for j in range(conf["workload"]):
            if j % conf["k"] != conf["child_index"]:
                continue
            try:
                # Class alternates per ROUND (j // k), not per slot: with
                # k=2 a per-slot alternation would hand one child only
                # points and the other only aggs.
                if (j // conf["k"]) % 2 == 1:
                    srv.run(
                        lambda key=keys[j % len(keys)]: q_point(key),
                        tenant=f"replica{conf['child_index']}",
                        lane="interactive",
                    )
                else:
                    t = srv.run(
                        q_agg, tenant=f"replica{conf['child_index']}", lane="batch"
                    )
                    agg_hash = _stable_table_hash(t)
                completed += 1
            except Exception:
                errors += 1
        result["mix"] = {
            "completed": completed,
            "errors": errors,
            "wall_s": round(_now() - t0, 3),
        }
        result["agg_hash"] = agg_hash
        result["fleet"] = _replicas.fleet_stats()
    finally:
        srv.close()
        _replicas.leave_fleet()
    with open(conf["out_path"], "w") as f:
        json.dump(result, f)


def _cache_section() -> dict:
    from hyperspace_tpu.engine.physical import device_cache_stats
    from hyperspace_tpu.engine.scan_cache import (
        global_bucketed_cache,
        global_concat_cache,
        global_scan_cache,
    )

    from hyperspace_tpu import resilience
    from hyperspace_tpu.telemetry import compile_log, metrics
    from hyperspace_tpu.telemetry.profiling import pallas_fallback_summary

    return {
        "cache_stats": {
            "scan": global_scan_cache().stats(),
            "bucketed_concat": global_bucketed_cache().stats(),
            "concat": global_concat_cache().stats(),
            "device_memo": device_cache_stats(),
        },
        # Session-level Pallas fallback counters: a silent host fallback of
        # the probe/sort kernels is a measurement hazard — surface it.
        "pallas_fallbacks": pallas_fallback_summary(),
        # Process-wide metrics registry: every cache/memo hit+miss (with
        # derived hit RATES), decode-pool work, rule applied/skipped counts,
        # kernel fallback counters, and quantile latency histograms
        # (p50/p90/p99 per histogram) — the perf trajectory records cache
        # BEHAVIOR alongside the timings (docs/observability.md).
        "metrics_snapshot": metrics.snapshot(),
        # Per-program XLA compile observatory: compiles / compile seconds /
        # traced shapes per jit entry point — the bench artifact records
        # WHAT compiled, so a compile-bound run (the r05 TPU timeout mode)
        # is attributable from the JSON alone.
        "compile_observatory": compile_log.program_summary(),
        # Reliability rollup: fault injections, retry traffic, quarantines,
        # and timeouts — `tools/bench_compare.py` gates on these (a bench
        # round that passed timings while silently retry-storming regressed).
        # ONE schema shared with the exporter frames
        # (`resilience.reliability_rollup`).
        "reliability": resilience.reliability_rollup(metrics.snapshot()),
    }


def _eviction_stress(s, make_query, d: dict) -> dict:
    """Clamp every cache budget far below the working set, re-run the indexed
    query, and verify (a) correctness survives, (b) evictions actually fire —
    the machinery is measured under pressure, not assumed (r3 weak item 4)."""
    from hyperspace_tpu.engine import physical as phys
    from hyperspace_tpu.engine.scan_cache import (
        global_bucketed_cache,
        global_concat_cache,
        global_scan_cache,
    )

    before_rows = d.get("join_rows")
    saved_dev = phys.device_cache_stats()["budget"]
    sc, bc, cc = global_scan_cache(), global_bucketed_cache(), global_concat_cache()
    saved = (sc.stats()["budget"], bc.stats()["budget"], cc.stats()["budget"])
    ev0 = (
        sc.stats()["evictions"]
        + bc.stats()["evictions"]
        + cc.stats()["evictions"]
        + phys.device_cache_stats()["evictions"]
    )
    try:
        phys.set_device_cache_budget(32 << 20)
        sc.set_capacity(64 << 20)
        bc.set_capacity(32 << 20)
        cc.set_capacity(32 << 20)
        t0 = _now()
        rows = make_query().count()
        stressed_cold = _now() - t0
        t0 = _now()
        rows2 = make_query().count()
        stressed_warm = _now() - t0
        assert rows == rows2
        ok = before_rows is None or rows == before_rows
        ev1 = (
            sc.stats()["evictions"]
            + bc.stats()["evictions"]
            + cc.stats()["evictions"]
            + phys.device_cache_stats()["evictions"]
        )
        return {
            "eviction_stress": {
                "budget_mb": {"device": 32, "scan": 64, "bucketed": 32, "concat": 32},
                "evictions_fired": ev1 - ev0,
                "correct": bool(ok),
                "stressed_cold_s": round(stressed_cold, 3),
                "stressed_warm_s": round(stressed_warm, 3),
            }
        }
    finally:
        phys.set_device_cache_budget(saved_dev)
        sc.set_capacity(saved[0])
        bc.set_capacity(saved[1])
        cc.set_capacity(saved[2])


def _variant_section(s, base, col, runs, hs) -> dict:
    """Beyond the headline int-key join: string-key join, filter-index point
    lookup, and data-skipping file pruning — each against its non-indexed
    counterpart on the same engine/hardware."""
    from hyperspace_tpu import IndexConfig
    from hyperspace_tpu.hyperspace import disable_hyperspace, enable_hyperspace
    from hyperspace_tpu.index.dataskipping import DataSkippingIndexConfig, MinMaxSketch

    n = int(os.environ.get("BENCH_VARIANT_ROWS", 500_000))
    rng = np.random.RandomState(9)

    def p50(fn):
        return round(timed_p50(fn, runs), 4)

    out = {}
    s.write_parquet(
        {
            "sku": np.array([f"sku-{i % 50_000:06d}" for i in range(n)]),
            "qty": rng.randint(1, 9, n).astype(np.int64),
        },
        os.path.join(base, "li_str"),
    )
    s.write_parquet(
        {
            "sku2": np.array([f"sku-{i:06d}" for i in range(50_000)]),
            "weight": rng.randint(1, 99, 50_000).astype(np.int64),
        },
        os.path.join(base, "dim_str"),
    )
    hs.create_index(
        s.read.parquet(os.path.join(base, "li_str")), IndexConfig("vLiStr", ["sku"], ["qty"])
    )
    hs.create_index(
        s.read.parquet(os.path.join(base, "dim_str")),
        IndexConfig("vDimStr", ["sku2"], ["weight"]),
    )

    def qs():
        l = s.read.parquet(os.path.join(base, "li_str"))
        dim = s.read.parquet(os.path.join(base, "dim_str"))
        return l.join(dim, col("sku") == col("sku2")).select("qty", "weight")

    disable_hyperspace(s)
    qs().count()
    out["string_join_scan_p50_s"] = p50(lambda: qs().count())
    enable_hyperspace(s)
    qs().count()
    out["string_join_indexed_p50_s"] = p50(lambda: qs().count())

    def qf():
        return (
            s.read.parquet(os.path.join(base, "dim_str"))
            .filter(col("sku2") == "sku-012345")
            .select("weight")
        )

    disable_hyperspace(s)
    qf().collect()
    out["filter_scan_p50_s"] = p50(lambda: qf().collect())
    enable_hyperspace(s)
    qf().collect()
    out["filter_indexed_p50_s"] = p50(lambda: qf().collect())

    # Data skipping: 16 range-partitioned files, MinMax sketch prunes 15.
    ds_dir = os.path.join(base, "events_ds")
    per = n // 16
    from hyperspace_tpu.engine import io as _eio
    from hyperspace_tpu.engine.table import Table as _T

    for i in range(16):
        t = {
            "ts": (np.arange(per, dtype=np.int64) + i * per),
            "val": rng.randint(0, 1000, per).astype(np.int64),
        }
        _eio.write_parquet(_T.from_pydict(t), os.path.join(ds_dir, f"part-{i:05d}.parquet"))
    hs.create_index(s.read.parquet(ds_dir), DataSkippingIndexConfig("vDs", [MinMaxSketch("ts")]))
    probe_ts = 3 * per + 7

    def qd():
        return s.read.parquet(ds_dir).filter(col("ts") == probe_ts).select("val")

    disable_hyperspace(s)
    qd().collect()
    out["dataskip_scan_p50_s"] = p50(lambda: qd().collect())
    enable_hyperspace(s)
    qd().collect()
    out["dataskip_indexed_p50_s"] = p50(lambda: qd().collect())
    out["dataskip_pruning_active"] = "pruned by" in qd().explain_string()

    # Hybrid Scan: append source files AFTER the index build, join with the
    # stale index + query-time shuffle-union of the appended rows (BASELINE
    # config 3). The appended files are re-read per query (their bucketization
    # depends on query-time source state), so this also exercises the per-file
    # scan cache level under its real workload.
    from hyperspace_tpu import IndexConstants as _IC
    from hyperspace_tpu.engine import io as _eio2
    from hyperspace_tpu.engine.table import Table as _T2

    hy_dir = os.path.join(base, "li_hybrid")
    n_h = n // 2
    s.write_parquet(
        {
            "hk": rng.randint(0, 20_000, n_h).astype(np.int64),
            "hv": rng.randint(1, 9, n_h).astype(np.int64),
        },
        hy_dir,
    )
    s.write_parquet(
        {
            "hk2": np.arange(20_000, dtype=np.int64),
            "hw": rng.randint(1, 99, 20_000).astype(np.int64),
        },
        os.path.join(base, "dim_hybrid"),
    )
    hs.create_index(s.read.parquet(hy_dir), IndexConfig("vHyL", ["hk"], ["hv"]))
    hs.create_index(
        s.read.parquet(os.path.join(base, "dim_hybrid")),
        IndexConfig("vHyR", ["hk2"], ["hw"]),
    )
    _eio2.write_parquet(
        _T2.from_pydict(
            {
                "hk": rng.randint(0, 20_000, n_h // 10).astype(np.int64),
                "hv": rng.randint(1, 9, n_h // 10).astype(np.int64),
            }
        ),
        os.path.join(hy_dir, "appended-00000.parquet"),
    )

    def qh():
        l = s.read.parquet(hy_dir)
        d = s.read.parquet(os.path.join(base, "dim_hybrid"))
        return l.join(d, col("hk") == col("hk2")).select("hv", "hw")

    # Skewed-key join: 40% of rows on ONE hot string key. The pre-classed
    # dense layout pads every bucket to the hot bucket's pow2 cap (a ~33x
    # padded-area blowup at this shape); the size-classed executor isolates
    # the hot bucket (outlier host merge / its own class) and pads the rest
    # tightly. Executor-isolated cold p50s: scan caches stay warm, device
    # memos cleared per run, measured classed vs dense on the same data.
    from hyperspace_tpu.engine.physical import clear_device_memos as _clear_memos
    from hyperspace_tpu.ops.bucket_join import ENV_SIZE_CLASSES as _ENV_SC

    n_hot = int(n * 0.4)
    sk = np.array([f"sk-{i % 20000:05d}" for i in range(n)])
    sk[:n_hot] = "sk-HOT"
    rng.shuffle(sk)
    s.write_parquet(
        {"sk": sk, "sv": rng.randint(1, 9, n).astype(np.int64)},
        os.path.join(base, "li_skew"),
    )
    s.write_parquet(
        {
            "sk2": np.array([f"sk-{i:05d}" for i in range(20000)] + ["sk-HOT"]),
            "sw": rng.randint(1, 99, 20001).astype(np.int64),
        },
        os.path.join(base, "dim_skew"),
    )
    hs.create_index(
        s.read.parquet(os.path.join(base, "li_skew")),
        IndexConfig("vSkL", ["sk"], ["sv"]),
    )
    hs.create_index(
        s.read.parquet(os.path.join(base, "dim_skew")),
        IndexConfig("vSkD", ["sk2"], ["sw"]),
    )

    def qk():
        l = s.read.parquet(os.path.join(base, "li_skew"))
        dim = s.read.parquet(os.path.join(base, "dim_skew"))
        return l.join(dim, col("sk") == col("sk2")).select("sv", "sw")

    disable_hyperspace(s)
    scan_rows = qk().count()
    out["skew_join_scan_p50_s"] = p50(lambda: qk().count())
    enable_hyperspace(s)
    expected_skew = qk().count()  # also warms the scan/bucketed caches
    out["skew_join_correct"] = expected_skew == scan_rows

    saved_sc = os.environ.get(_ENV_SC)

    def exec_cold_p50(classed: bool, n_runs: int) -> float:
        os.environ[_ENV_SC] = "1" if classed else "0"
        times = []
        for _ in range(n_runs):
            _clear_memos()
            t0 = _now()
            assert qk().count() == expected_skew
            times.append(_now() - t0)
        return round(float(np.percentile(times, 50)), 4)

    try:
        out["skew_exec_classed_p50_s"] = exec_cold_p50(True, runs)
        # The dense layout is known-slow at this shape: two runs bound the
        # bench budget while still giving a median.
        out["skew_exec_dense_p50_s"] = exec_cold_p50(False, 2)
    finally:
        if saved_sc is None:
            os.environ.pop(_ENV_SC, None)
        else:
            os.environ[_ENV_SC] = saved_sc
    if out["skew_exec_classed_p50_s"] > 0:
        out["skew_classed_speedup"] = round(
            out["skew_exec_dense_p50_s"] / out["skew_exec_classed_p50_s"], 2
        )
    qk().count()  # warm pairs memo
    out["skew_join_indexed_p50_s"] = p50(lambda: qk().count())

    disable_hyperspace(s)
    qh().count()
    out["hybrid_scan_p50_s"] = p50(lambda: qh().count())
    expected_rows = qh().count()
    enable_hyperspace(s)
    s.conf.set(_IC.INDEX_HYBRID_SCAN_ENABLED, "true")
    qh().count()
    out["hybrid_indexed_p50_s"] = p50(lambda: qh().count())
    out["hybrid_correct"] = qh().count() == expected_rows
    out["hybrid_uses_index"] = "vHyL" in qh().explain_string()
    s.conf.set(_IC.INDEX_HYBRID_SCAN_ENABLED, "false")
    return out


def _device_section(s, base, col, runs, backend) -> dict:
    """Isolate the on-device kernels from the end-to-end query and time each via
    block_until_ready deltas: (a) the pad+sort that builds the padded rep,
    (b) the XLA searchsorted probe, (c) the Pallas tiled-compare probe (TPU
    kernel; interpret-mode elsewhere — reported only on tpu unless forced).
    Bytes are the ACTUAL device matrix sizes (measured, not modeled); the
    roofline utilization divides achieved traffic by the backend's HBM peak."""
    import jax

    from hyperspace_tpu.engine import physical as phys

    l = s.read.parquet(os.path.join(base, "lineitem"))
    o = s.read.parquet(os.path.join(base, "orders"))
    df = l.join(o, col("orderkey") == col("o_orderkey")).select("qty", "o_custkey")
    plan = df.physical_plan()
    join_exec = None
    stack = [plan]
    while stack:
        node = stack.pop()
        if isinstance(node, phys.SortMergeJoinExec) and node.bucketed:
            join_exec = node
            break
        stack.extend(node.children())
    if join_exec is None:
        return {"device_note": "no bucketed join in plan"}

    from hyperspace_tpu.engine.physical import ExecContext, _padded_rep
    from hyperspace_tpu.ops.bucket_join import (
        _probe,
        probe_keys_promoted,
        probe_orientation,
    )

    ctx = ExecContext(session=s)
    left, l_starts = join_exec.left.execute_concat(ctx)
    right, r_starts = join_exec.right.execute_concat(ctx)
    l_rep = _padded_rep(left, l_starts, join_exec.left_keys)
    r_rep = _padded_rep(right, r_starts, join_exec.right_keys)
    if l_rep.mode != r_rep.mode:
        if l_rep.mode == "value":
            l_rep = _padded_rep(left, l_starts, join_exec.left_keys, force_hash=True)
        else:
            r_rep = _padded_rep(right, r_starts, join_exec.right_keys, force_hash=True)
    a, b, _sw = probe_orientation(l_rep, r_rep)
    lk, rk = probe_keys_promoted(a.keys, b.keys)

    out = {}

    # (a) pad+sort: the PRODUCTION rep constructor — the size-classed build
    # (both sides, per-class matrices + outlier split), with the per-class
    # breakdown, plus the pre-classed dense kernel as the reference point.
    from hyperspace_tpu.ops.backend import use_device_path
    from hyperspace_tpu.ops.bucket_join import (
        build_classed_plan,
        pad_buckets_by_hash,
    )
    from hyperspace_tpu.ops.hashing import key64

    import jax.numpy as jnp

    key_cols = [left.column(c) for c in join_exec.left_keys]
    k64 = key64(key_cols, [jnp.asarray(c.data) for c in key_cols])
    r_key_cols = [right.column(c) for c in join_exec.right_keys]
    rk64 = key64(r_key_cols, [jnp.asarray(c.data) for c in r_key_cols])
    jax.block_until_ready((k64, rk64))
    k64_np, rk64_np = np.asarray(k64), np.asarray(rk64)
    device = use_device_path()

    def pad_classed_once(timings=None):
        plan = build_classed_plan(
            k64_np, rk64_np, l_starts, r_starts, "hash",
            device=device, timings=timings,
        )
        if device:
            jax.block_until_ready([seg.l.keys for seg in plan.segments])
        return plan

    breakdown = []
    pad_classed_once(breakdown)  # warm compiles + one-run class breakdown
    out["pad_sort_p50_s"] = round(timed_p50(pad_classed_once, runs), 5)
    out["pad_sort_classes"] = breakdown

    def pad_dense_once():
        rep = pad_buckets_by_hash(k64, l_starts)
        jax.block_until_ready(rep.keys)

    pad_dense_once()  # compile
    out["pad_sort_dense_p50_s"] = round(timed_p50(pad_dense_once, runs), 5)

    # (b) the XLA probe production dispatches.
    def xla_probe():
        jax.block_until_ready(_probe(lk, rk, a.lengths, b.lengths))

    xla_probe()  # compile
    from hyperspace_tpu.telemetry.profiling import annotate, trace

    profiling = bool(os.environ.get("BENCH_PROFILE_DIR"))
    times = []
    with trace(os.environ.get("BENCH_PROFILE_DIR")):
        for _ in range(runs):
            t0 = _now()
            with annotate("bucketed-probe", enabled=profiling):
                xla_probe()
            times.append(_now() - t0)
    out["device_time_s"] = round(float(np.percentile(times, 50)), 5)

    # (c) the Pallas tiled-compare probe vs the XLA probe, apples-to-apples at
    # a BOUNDED sub-shape: the tiled compare is quadratic per bucket, so at
    # full bench caps it would run for minutes; a sorted-prefix slice of both
    # sides keeps the comparison honest (prefixes of sorted rows stay sorted,
    # lengths clamp) inside the kernel's win region.
    if backend == "tpu" or os.environ.get("HYPERSPACE_PALLAS_PROBE") == "1":
        try:
            import jax.numpy as jnp

            from hyperspace_tpu.ops.pallas_probe import probe_pallas, shape_supported

            cap_l2 = min(int(lk.shape[1]), 4096)
            cap_r2 = min(int(rk.shape[1]), 512)
            if not shape_supported(int(lk.shape[0]), cap_l2, cap_r2):
                raise ValueError(
                    f"unsupported pallas shape B={int(lk.shape[0])}"
                )
            lk2, rk2 = lk[:, :cap_l2], rk[:, :cap_r2]
            al2 = jnp.minimum(a.lengths, cap_l2)
            bl2 = jnp.minimum(b.lengths, cap_r2)

            def pl_probe():
                jax.block_until_ready(probe_pallas(lk2, rk2, al2, bl2))

            def xla_probe_sub():
                jax.block_until_ready(_probe(lk2, rk2, al2, bl2))

            pl_probe()  # compile
            xla_probe_sub()
            out["pallas_probe_sub_p50_s"] = round(timed_p50(pl_probe, runs), 5)
            out["xla_probe_sub_p50_s"] = round(timed_p50(xla_probe_sub, runs), 5)
            out["probe_sub_shape"] = [int(lk.shape[0]), cap_l2, cap_r2]
        except Exception as e:
            out["pallas_probe_error"] = f"{type(e).__name__}: {e}"[:300]

    # (d) the Pallas in-VMEM bitonic sort vs the XLA argsort, at a bounded
    # sub-shape inside the kernel's VMEM budget (same honesty contract as the
    # probe comparison: prefix slices of the real padded matrices).
    if backend == "tpu" or os.environ.get("HYPERSPACE_PALLAS_SORT") == "1":
        try:
            import jax.numpy as jnp

            from hyperspace_tpu.ops.pallas_sort import (
                shape_supported as sort_shape_ok,
                sort_padded_with_order,
            )

            cap_s = min(int(lk.shape[1]), 8192)
            Bs = int(lk.shape[0])
            if not sort_shape_ok(Bs, cap_s):
                raise ValueError(f"unsupported pallas sort shape B={Bs} cap={cap_s}")
            if not jnp.issubdtype(lk.dtype, jnp.integer):
                raise ValueError(f"sort comparison needs integer keys, got {lk.dtype}")
            unsorted = jnp.flip(lk[:, :cap_s].astype(jnp.int64), axis=1)  # de-sort

            def pl_sort():
                jax.block_until_ready(sort_padded_with_order(unsorted))

            def xla_sort():
                order = jnp.argsort(unsorted, axis=1)
                jax.block_until_ready(
                    (jnp.take_along_axis(unsorted, order, axis=1), order)
                )

            pl_sort()  # compile
            xla_sort()
            out["pallas_sort_sub_p50_s"] = round(timed_p50(pl_sort, runs), 5)
            out["xla_sort_sub_p50_s"] = round(timed_p50(xla_sort, runs), 5)
            out["sort_sub_shape"] = [Bs, cap_s]
        except Exception as e:
            out["pallas_sort_error"] = f"{type(e).__name__}: {e}"[:300]

    # Measured traffic: the probe reads both padded key matrices; pad+sort
    # reads+writes the left one.
    probe_bytes = int(lk.nbytes) + int(rk.nbytes)
    out["device_key_bytes"] = probe_bytes
    peak = _PEAK_BW.get(backend, _PEAK_BW["cpu"])
    if out["device_time_s"] > 0:
        achieved = probe_bytes / out["device_time_s"]
        out["achieved_gbps"] = round(achieved / 1e9, 2)
        out["peak_gbps"] = round(peak / 1e9, 1)
        out["utilization"] = round(achieved / peak, 4)
    return out


def _device_observatory_section(s, base, col, runs) -> dict:
    """`bench_detail.device_observatory`: run a representative join+agg mix
    with ``HYPERSPACE_DEVICE_TIMING=all`` and report what the observatory
    attributed — per-label device time, H2D/D2H bytes (+seconds where
    timed), per-site pow2 pad ratios, and the effective H2D GB/s next to a
    MEASURED host memcpy peak (numpy copy of a 64 MiB buffer — the honest
    ceiling for a CPU 'transfer', which is a memcpy)."""
    if os.environ.get("BENCH_SKIP_OBSERVATORY") == "1":
        return {"skipped": True}
    import numpy as np

    from hyperspace_tpu.telemetry import device_observatory as _devobs

    l = s.read.parquet(os.path.join(base, "lineitem"))
    o = s.read.parquet(os.path.join(base, "orders"))

    def mix():
        l.join(o, col("orderkey") == col("o_orderkey")).select(
            "qty", "o_custkey"
        ).collect()
        l.filter(col("qty") > 25).group_by("orderkey").agg(
            p=("price", "sum")
        ).collect()

    saved = {
        k: os.environ.get(k)
        for k in ("HYPERSPACE_DEVICE_TIMING", "HYPERSPACE_DEVICE_TIMING_INTERVAL_S")
    }
    os.environ["HYPERSPACE_DEVICE_TIMING"] = "all"
    os.environ["HYPERSPACE_DEVICE_TIMING_INTERVAL_S"] = "0"
    _devobs.reset()
    try:
        from hyperspace_tpu.engine import physical as phys

        mix()  # warm/compile pass
        # Measured pass: compiles are warm but the device memos are cleared,
        # so the staging/pad/transfer work actually happens and is attributed
        # (a fully-memoized pass would honestly report all-zeros).
        phys.clear_device_memos()
        _devobs.reset()
        t0 = _now()
        mix()
        wall = _now() - t0
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v

    programs = _devobs.device_summary()
    transfers = _devobs.transfer_summary()
    pads = _devobs.pad_summary()
    dev_total = round(sum(p["device_s"] for p in programs.values()), 6)
    top = sorted(programs.items(), key=lambda kv: -kv[1]["device_s"])[:12]

    # Measured memcpy peak: what "H2D" can possibly sustain on this host.
    buf = np.ones(64 * 1024 * 1024 // 8, dtype=np.float64)
    dst = np.empty_like(buf)
    t0 = _now()
    np.copyto(dst, buf)
    memcpy_s = max(_now() - t0, 1e-9)
    memcpy_gbps = round(buf.nbytes / memcpy_s / 1e9, 2)

    out = {
        "wall_s": round(wall, 4),
        "device_time_s": dev_total,
        "device_share": round(dev_total / wall, 4) if wall else None,
        "programs_top": {lbl: p for lbl, p in top},
        "programs_total": len(programs),
        "transfers": transfers,
        "pads": pads,
        "memcpy_peak_gbps": memcpy_gbps,
    }
    h2d = transfers.get("h2d") or {}
    if h2d.get("gb_per_s") is not None:
        out["h2d_vs_memcpy_peak"] = round(h2d["gb_per_s"] / memcpy_gbps, 4)
    return out


def run_mesh_bench() -> dict:
    """`bench_detail.mesh`: the sharded path on the FORCED VIRTUAL 8-device CPU
    mesh (multi-chip hardware is not reachable from the bench host — these
    numbers demonstrate the mesh path works and its compile contract holds;
    they are NOT chip-count speedups). Measures build_s and the indexed-join
    p50 at 1 device (`HYPERSPACE_DISTRIBUTED=0`, the exact fallback) vs the
    8-device mesh, the exchange traffic counters, and the compile
    observatory's `parallel.*` program counts — HARD-asserting that each mesh
    device program compiled exactly once across all section queries and that
    the armed compile watchdog never fired (a hung compile fails the section
    instead of stalling the bench: the r05 failure mode, now classified)."""
    from hyperspace_tpu.parallel.mesh import force_virtual_cpu

    n_dev = int(os.environ.get("BENCH_MESH_DEVICES", os.environ.get("BENCH_DIST_DEVICES", 8)))
    force_virtual_cpu(n_dev)
    # Compile watchdog armed for the whole section: a runaway compile becomes
    # a classified CompileTimeoutError in the section result, never a stall.
    os.environ.setdefault("HYPERSPACE_COMPILE_TIMEOUT_S", "300")
    n_l = int(os.environ.get("BENCH_MESH_ROWS", os.environ.get("BENCH_DIST_LINEITEM_ROWS", 400_000)))
    n_o = int(os.environ.get("BENCH_DIST_ORDERS_ROWS", 50_000))
    # Pin ONE workload class for the whole section: the row quantum (the
    # deploy knob for exactly this) set to the LARGEST table's shard size puts
    # both tables' builds, exchanges, and probes on identical device-program
    # shapes — each parallel.* program compiles once for the section.
    quantum = 1 << (max(1, -(-max(n_l, n_o) // n_dev)) - 1).bit_length()
    os.environ.setdefault("HYPERSPACE_MESH_ROW_QUANTUM", str(quantum))

    from hyperspace_tpu import IndexConfig, IndexConstants
    from hyperspace_tpu.engine import HyperspaceSession, col
    from hyperspace_tpu.engine.scan_cache import global_concat_cache, global_scan_cache
    from hyperspace_tpu.hyperspace import Hyperspace, disable_hyperspace, enable_hyperspace
    from hyperspace_tpu.parallel.table_ops import DIST_JOIN_STATS
    from hyperspace_tpu.telemetry import compile_log, metrics

    runs = int(os.environ.get("BENCH_RUNS", 3))
    base = tempfile.mkdtemp(prefix="hs_mbench_")
    mesh_labels = ("parallel.exchange_counts", "parallel.exchange", "parallel.probe")
    try:
        s = HyperspaceSession(warehouse=base)
        s.conf.set(IndexConstants.INDEX_SYSTEM_PATH, os.path.join(base, "indexes"))
        s.conf.set(IndexConstants.INDEX_NUM_BUCKETS, 64)
        s.conf.set(IndexConstants.DISTRIBUTED_MIN_ROWS, 0)
        rng = np.random.RandomState(7)
        s.write_parquet(
            {
                "orderkey": rng.randint(0, n_o, n_l).astype(np.int64),
                "qty": rng.randint(1, 51, n_l).astype(np.int64),
            },
            os.path.join(base, "lineitem"),
        )
        s.write_parquet(
            {
                "o_orderkey": np.arange(n_o, dtype=np.int64),
                "o_custkey": rng.randint(0, 10_000, n_o).astype(np.int64),
            },
            os.path.join(base, "orders"),
        )

        def query():
            l = s.read.parquet(os.path.join(base, "lineitem"))
            o = s.read.parquet(os.path.join(base, "orders"))
            return l.join(o, col("orderkey") == col("o_orderkey")).select("qty", "o_custkey")

        def build(tag):
            hs = Hyperspace(s)
            t0 = _now()
            hs.create_index(
                s.read.parquet(os.path.join(base, "lineitem")),
                IndexConfig(f"liIdx{tag}", ["orderkey"], ["qty"]),
            )
            hs.create_index(
                s.read.parquet(os.path.join(base, "orders")),
                IndexConfig(f"ordIdx{tag}", ["o_orderkey"], ["o_custkey"]),
            )
            return _now() - t0, hs

        def indexed_p50():
            enable_hyperspace(s)
            query().count()  # warm-up: block layouts + any compile
            times = []
            for _ in range(runs):
                t0 = _now()
                query().count()
                times.append(_now() - t0)
            return round(float(np.percentile(times, 50)), 3)

        c0 = metrics.snapshot()["counters"]

        # -- 1 device: the exact single-device fallback ---------------------
        os.environ["HYPERSPACE_DISTRIBUTED"] = "0"
        single_build_s, hs = build("S")
        single_join_p50 = indexed_p50()
        single_rows = query().count()
        for name in ("liIdxS", "ordIdxS"):
            hs.delete_index(name)

        # -- 8-device mesh --------------------------------------------------
        global_scan_cache().clear()
        global_concat_cache().clear()
        os.environ["HYPERSPACE_DISTRIBUTED"] = "1"
        mesh_build_s, _hs = build("M")
        enable_hyperspace(s)
        query().count()  # warm-up: block layouts upload HERE, once
        # Steady-state baseline AFTER the warm-up: the timed runs (and the
        # repeats below) must show ZERO further block builds — the reuse
        # signal the counters exist to prove.
        b0, p0 = DIST_JOIN_STATS["block_builds"], DIST_JOIN_STATS["probes"]
        mesh_join_p50 = indexed_p50()
        mesh_rows = query().count()
        assert mesh_rows == single_rows, (mesh_rows, single_rows)

        # Repeat queries through the mesh path: every parallel.* device
        # program must have compiled exactly once for the whole section.
        for _ in range(2):
            query().count()
        summary = compile_log.program_summary()
        compiles = {lbl: summary.get(lbl, {}).get("compiles", 0) for lbl in mesh_labels}
        for lbl, n_compiles in compiles.items():
            assert n_compiles <= 1, f"{lbl} compiled {n_compiles}x: shapes unquantized"
        assert compiles["parallel.exchange"] == 1, compiles
        deadline_hits = metrics.snapshot()["counters"].get(
            "xla.compiles.deadline_exceeded", 0
        )
        assert deadline_hits == 0, "compile watchdog fired inside the mesh section"
        # Steady state: nothing after the warm-up re-uploaded a block layout.
        assert DIST_JOIN_STATS["block_builds"] == b0, "block layouts re-uploaded"

        c1 = metrics.snapshot()["counters"]

        def delta(key):
            return int(c1.get(key, 0) - c0.get(key, 0))

        # -- device-resident codes over the mesh wire -----------------------
        # String-key builds with HYPERSPACE_ENCODED_DEVICE on vs off: the
        # coded exchange sends (narrow bucket + int8 validity + int32 rowid +
        # int8 codes) where the flat one sends (u32 hash + i32 validity + i64
        # rowid + i32 codes). Runs AFTER the compile-once asserts — the coded
        # and string-sort shapes are their own compile classes, outside the
        # int-key workload those asserts pin. `_finish` folds this dict into
        # `bench_detail.encoded_device` next to the H2D half.
        n_enc = int(os.environ.get("BENCH_ENCODED_DEVICE_ROWS", 300_000))
        card = 100  # int8 code class
        dictionary = np.asarray([f"sku#{i:04d}" for i in range(card)])
        s.write_parquet(
            {
                "sk": dictionary[rng.randint(0, card, n_enc)],
                "v": rng.randint(0, 1000, n_enc).astype(np.int64),
            },
            os.path.join(base, "fact_encdev"),
        )
        disable_hyperspace(s)
        enc = {"rows": n_enc, "key_cardinality": card}
        saved_ed = os.environ.get("HYPERSPACE_ENCODED_DEVICE")
        try:
            from hyperspace_tpu.engine.physical import clear_device_memos
            from hyperspace_tpu.engine.scan_cache import (
                global_bucketed_cache,
                global_filtered_cache,
            )

            for label, flag in (("on", "1"), ("off", "0")):
                os.environ["HYPERSPACE_ENCODED_DEVICE"] = flag
                global_scan_cache().clear()
                global_concat_cache().clear()
                global_filtered_cache().clear()
                global_bucketed_cache().clear()
                clear_device_memos()
                m0 = metrics.counter("parallel.exchange.bytes_moved").value
                t0 = _now()
                hs.create_index(
                    s.read.parquet(os.path.join(base, "fact_encdev")),
                    IndexConfig(f"encDev{label}", ["sk"], ["v"]),
                )
                enc[f"build_{label}_s"] = round(_now() - t0, 3)
                enc[f"exchange_bytes_moved_{label}"] = (
                    metrics.counter("parallel.exchange.bytes_moved").value - m0
                )
                hs.delete_index(f"encDev{label}")
            enc["bytes_moved_reduction_x"] = round(
                enc["exchange_bytes_moved_off"]
                / max(enc["exchange_bytes_moved_on"], 1),
                2,
            )
        finally:
            if saved_ed is None:
                os.environ.pop("HYPERSPACE_ENCODED_DEVICE", None)
            else:
                os.environ["HYPERSPACE_ENCODED_DEVICE"] = saved_ed

        # -- bit-packed sub-byte lanes over the mesh wire --------------------
        # Same build, card 12 (the 4-bit lane class), ENCODED on for BOTH
        # legs: packing on vs off isolates what the sub-byte wire classes buy
        # BELOW the int8 coded exchange (4-bit key + 6-bit bucket + 1-bit
        # validity + 16-bit rowid vs int8/int8/int8/int32). `_finish` folds
        # this into `bench_detail.packed_codes`.
        n_pk = int(os.environ.get("BENCH_PACKED_MESH_ROWS", 60_000))
        card_pk = 12
        dict_pk = np.asarray([f"cat#{i:02d}" for i in range(card_pk)])
        s.write_parquet(
            {
                "pk": dict_pk[rng.randint(0, card_pk, n_pk)],
                "v": rng.randint(0, 1000, n_pk).astype(np.int64),
            },
            os.path.join(base, "fact_packedmesh"),
        )
        pk = {"rows": n_pk, "key_cardinality": card_pk}
        saved_flags = {
            k: os.environ.get(k)
            for k in ("HYPERSPACE_ENCODED_DEVICE", "HYPERSPACE_PACKED_CODES")
        }
        try:
            from hyperspace_tpu.engine.physical import clear_device_memos
            from hyperspace_tpu.engine.scan_cache import (
                global_bucketed_cache,
                global_filtered_cache,
            )

            os.environ["HYPERSPACE_ENCODED_DEVICE"] = "1"
            for label, flag in (("on", "1"), ("off", "0")):
                os.environ["HYPERSPACE_PACKED_CODES"] = flag
                global_scan_cache().clear()
                global_concat_cache().clear()
                global_filtered_cache().clear()
                global_bucketed_cache().clear()
                clear_device_memos()
                m0 = metrics.counter("parallel.exchange.bytes_moved").value
                t0 = _now()
                hs.create_index(
                    s.read.parquet(os.path.join(base, "fact_packedmesh")),
                    IndexConfig(f"pkMesh{label}", ["pk"], ["v"]),
                )
                pk[f"build_{label}_s"] = round(_now() - t0, 3)
                pk[f"exchange_bytes_moved_{label}"] = (
                    metrics.counter("parallel.exchange.bytes_moved").value - m0
                )
                hs.delete_index(f"pkMesh{label}")
            pk["bytes_moved_reduction_x"] = round(
                pk["exchange_bytes_moved_off"]
                / max(pk["exchange_bytes_moved_on"], 1),
                2,
            )
        finally:
            for k, v in saved_flags.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v

        return {
            "encoded_device": enc,
            "packed_codes": pk,
            # These run on ONE host pretending to be 8 devices — never quote
            # them as speedups (r3 weak item 6).
            "virtual_mesh": True,
            "devices": n_dev,
            "rows": n_l,
            "build_mesh_s": round(mesh_build_s, 3),
            "build_single_s": round(single_build_s, 3),
            "indexed_join_mesh_p50_s": mesh_join_p50,
            "indexed_join_single_p50_s": single_join_p50,
            "join_rows": int(mesh_rows),
            "exchange": {
                "rows": delta("parallel.exchange.rows"),
                "bytes_payload": delta("parallel.exchange.bytes_payload"),
                "bytes_moved": delta("parallel.exchange.bytes_moved"),
                "count": delta("parallel.exchange.count"),
            },
            "compile_observatory": {
                lbl: summary.get(lbl, {}) for lbl in mesh_labels
            },
            "compile_once": True,  # hard-asserted above
            "watchdog_triggered": False,  # hard-asserted above
            "compile_cache": compile_log.compile_cache_summary(),
            "steady_block_builds": DIST_JOIN_STATS["block_builds"] - b0,
            "steady_probes": DIST_JOIN_STATS["probes"] - p0,
        }
    finally:
        os.environ.pop("HYPERSPACE_DISTRIBUTED", None)
        shutil.rmtree(base, ignore_errors=True)


def _child_main():
    faulthandler.enable()
    faulthandler.register(signal.SIGUSR1, all_threads=True)
    if os.environ.get(_CHILD_ENV) == "dist":
        _enable_compile_cache()  # the mesh section reports cache traffic
        print(json.dumps(run_mesh_bench()), flush=True)
        return
    if os.environ.get(_CHILD_ENV) == "replica":
        _replica_child_main()
        return
    t_start = _now()
    _enable_compile_cache()
    # Init handshake: the parent aborts early when the backend claim is wedged
    # (observed failure mode: jax.devices() blocks forever on the terminal claim).
    import jax

    print(f"BENCH_CHILD_INIT_OK {jax.devices()[0].platform}", flush=True)
    # A broken-but-responsive backend answers UNAVAILABLE after tens of
    # minutes: if the parent already moved on (abandon sentinel), release the
    # claim immediately with a clean exit instead of racing a fallback bench.
    abandon = os.environ.get("BENCH_ABANDON_FILE")
    if abandon and os.path.exists(abandon):
        try:
            print(json.dumps({"abandoned": True}), flush=True)
        except Exception:
            pass  # parent long gone (broken pipe): still exit 0 = clean release
        return
    # Child-side deadline: finish (skipping phases) INSIDE the parent's budget
    # so the exit is clean — a parent kill of a claim-holding child wedges the
    # terminal. 90 s margin covers result emission + interpreter teardown.
    deadline = t_start + max(_CHILD_TIMEOUT_S - 90, 60)

    # Deadline-overrun watchdog: phase-boundary deadlines cannot interrupt a
    # PJRT call that never returns (round 5: the builds compile hung forever
    # in a connection-refused retry loop after the relay process died, so the
    # parent's run-timeout killed the child and the salvage lost everything
    # after the last whole-phase partial). If the deadline is >60 s past and
    # the main thread still hasn't finished, either a dispatch is hung or an
    # in-flight phase has overrun the whole budget — both end in the parent's
    # kill at _CHILD_TIMEOUT_S (deadline+90), so exiting at +60 with a
    # salvage record is strictly better than dying silent. The label stays
    # honest about the ambiguity, and the exit path must never raise: a
    # mid-mutation json.dumps (the main thread may still be running) falls
    # back to a minimal record so the parent always sees a parseable final
    # line instead of misclassifying the child as crashed.
    bench_done = threading.Event()

    def _overrun_watchdog():
        while True:
            time.sleep(10)
            # _BENCH_RESULT: the run finished and is merely tearing down its
            # tempdir — the real final record is about to print; never
            # supersede it with a salvage stamped as an abort.
            if bench_done.is_set() or _BENCH_RESULT:
                return
            if _now() <= deadline + 60:
                continue
            try:
                snap = dict(_LIVE_PHASES[-1].out) if _LIVE_PHASES else {}
                snap["aborted_at"] = "watchdog-deadline-overrun (dispatch hung or phase overran)"
                lines = (
                    _PARTIAL_TAG + json.dumps(snap) + "\n" + json.dumps(_metric_from(snap))
                )
            except Exception:
                lines = json.dumps(
                    {
                        "metric": "watchdog-salvage",
                        "value": 0.0,
                        "unit": "s",
                        "vs_baseline": None,
                        "detail": {"aborted_at": "watchdog-deadline-overrun"},
                    }
                )
            try:
                with _print_lock:
                    # Re-check under the lock: a run that completed in the
                    # last instant must win — its final record is already
                    # printed (or about to be, by a main thread holding
                    # bench_done) and must stay the LAST stdout line.
                    if bench_done.is_set() or _BENCH_RESULT:
                        return
                    print(lines, flush=True)
            except Exception:
                pass
            # run_bench's `finally: rmtree` never runs on _exit: drop the
            # bench tempdir here (~0.5 GB of parquet at the 8M default).
            if _BENCH_TMPDIR:
                shutil.rmtree(_BENCH_TMPDIR[-1], ignore_errors=True)
            os._exit(0)

    threading.Thread(target=_overrun_watchdog, daemon=True).start()
    result = run_bench(deadline)
    bench_done.set()
    with _print_lock:
        print(json.dumps(result), flush=True)


def _run_mesh_subprocess() -> dict:
    env = dict(os.environ)
    env[_CHILD_ENV] = "dist"
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)  # force_virtual_cpu sets its own
    try:
        r = subprocess.run(
            [sys.executable, os.path.abspath(__file__)],
            env=env,
            capture_output=True,
            text=True,
            timeout=int(
                os.environ.get(
                    "BENCH_MESH_TIMEOUT_S", os.environ.get("BENCH_DIST_TIMEOUT_S", 300)
                )
            ),
        )
        if r.returncode == 0 and r.stdout.strip():
            return json.loads(r.stdout.strip().splitlines()[-1])
        return {"error": f"rc={r.returncode}", "stderr": r.stderr.strip()[-400:]}
    except subprocess.TimeoutExpired:
        return {"error": "timeout", "virtual_mesh": True}
    except (ValueError, KeyError) as e:
        return {"error": f"{type(e).__name__}: {e}"}


def _tpu_child_attempt(diag: dict, abandon_file: str):
    """One supervised TPU bench child. Returns (result|None, state) where state
    is one of "ok", "init-timeout", "run-timeout", "crashed", "salvaged".

    Kill discipline (TPU_EVIDENCE.md): a client killed mid-claim wedges the
    terminal for the session, so an init-stuck child is NEVER killed — the
    parent writes the abandon sentinel (the child exits cleanly the moment its
    init finally answers) and moves on. The child also ends ITSELF inside its
    budget (`_Phases` deadline), so the parent's run-timeout kill only fires
    for a truly hung dispatch."""
    import threading

    env = dict(os.environ)
    env[_CHILD_ENV] = "1"
    env.setdefault("JAX_PLATFORMS", "axon")
    env["BENCH_ABANDON_FILE"] = abandon_file
    env.setdefault("HYPERSPACE_COMPILE_CACHE_DIR", _COMPILE_CACHE_DIR)
    p = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__)],
        env=env,
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        text=True,
    )
    out_lines, err_chunks, partials = [], [], []
    init_ok = threading.Event()
    child_platform = [None]

    def _rd_out():
        for line in p.stdout:
            if line.startswith(_PARTIAL_TAG):
                partials.append(line[len(_PARTIAL_TAG):])
                # Tee to stderr immediately: live progress is observable and
                # survives even if this supervisor dies before the child.
                print(line.rstrip(), file=sys.stderr, flush=True)
                continue
            out_lines.append(line)
            if line.startswith("BENCH_CHILD_INIT_OK"):
                child_platform[0] = line.split()[-1].strip()
                init_ok.set()
                print(line.rstrip(), file=sys.stderr, flush=True)

    def _rd_err():
        err_chunks.append(p.stderr.read() or "")

    t_out = threading.Thread(target=_rd_out, daemon=True)
    t_err = threading.Thread(target=_rd_err, daemon=True)
    t_out.start()
    t_err.start()

    # Two-stage budget: a wedged/broken terminal hangs backend init for tens of
    # minutes, so INIT gets a bounded deadline; after init reports, the full
    # budget covers compile + the bench itself. The deadline is generous
    # (300 s) because a terminal RECYCLING a just-released claim can
    # legitimately delay the grant.
    init_timeout = int(os.environ.get("BENCH_TPU_INIT_TIMEOUT_S", 300))
    deadline = _now() + init_timeout
    while not init_ok.is_set() and p.poll() is None and _now() < deadline:
        init_ok.wait(timeout=1)

    if not init_ok.is_set() and p.poll() is None:
        # Init-stuck: NO kill (the wedge trigger). Abandon and move on; the
        # child exits cleanly whenever the terminal finally answers.
        stage = f"init-timeout ({init_timeout}s); child left to exit cleanly"
        with open(abandon_file, "w") as f:
            f.write(str(os.getpid()))
        diag["attempts"].append({"rc": stage, "platform": None})
        return None, "init-timeout"

    timed_out = False
    stage = ""
    try:
        p.wait(timeout=_CHILD_TIMEOUT_S)
    except subprocess.TimeoutExpired:
        timed_out = True
        stage = f"run-timeout ({_CHILD_TIMEOUT_S}s)"
    if timed_out:
        # The child blew through its own internal deadline => a dispatch is
        # genuinely hung. Stack-dump then kill as the last resort; the
        # artifact records WHERE it froze.
        p.send_signal(signal.SIGUSR1)
        try:
            p.wait(timeout=15)
        except subprocess.TimeoutExpired:
            p.kill()
            p.wait()
    t_out.join(timeout=5)
    t_err.join(timeout=5)
    err = "".join(err_chunks)
    out = "".join(out_lines)

    if timed_out:
        diag["attempts"].append(
            {
                "rc": stage,
                "platform": child_platform[0],
                "stderr_stack_tail": err.strip()[-1500:],
            }
        )
        # Salvage: the last completed phase snapshot is still a real
        # on-device measurement — report it rather than falling back blind.
        if partials:
            try:
                d = json.loads(partials[-1])
                d["aborted_at"] = stage
                result = _metric_from(d)
                diag["probe"] = "tpu child timed out; last partial snapshot reported"
                return result, "salvaged"
            except ValueError:
                pass
        return None, "run-timeout"

    diag["attempts"].append(
        {
            "rc": p.returncode,
            "platform": child_platform[0],
            "stderr": err.strip()[-800:],
        }
    )
    if p.returncode == 0 and out.strip():
        try:
            result = json.loads(out.strip().splitlines()[-1])
            if not result.get("abandoned"):
                return result, "ok"
        except (ValueError, KeyError, IndexError) as e:
            diag["attempts"][-1]["parse_error"] = f"{type(e).__name__}: {e}"
    return None, "crashed"


def main():
    if os.environ.get(_CHILD_ENV):
        _child_main()
        return
    t_setup0 = _now()
    diag = {"attempts": []}
    if not os.environ.get("BENCH_FORCE_CPU"):
        # Unique per run (a pid-keyed name could collide with a stale sentinel
        # from an earlier run and silently disable the TPU bench forever).
        abandon_dir = tempfile.mkdtemp(prefix="bench_abandon_")
        abandon_file = os.path.join(abandon_dir, "abandon")
        result, state = _tpu_child_attempt(diag, abandon_file)
        if result is None and state == "crashed":
            # The crashed child exited => its claim released cleanly; one
            # retry distinguishes a transient failure from a broken backend.
            diag["retry"] = "child crashed after init; retrying once"
            print(json.dumps({"warning": diag["retry"]}), file=sys.stderr)
            result, state = _tpu_child_attempt(diag, abandon_file)
        if state != "init-timeout":
            # Abandoned child still watches the sentinel dir: only remove it
            # when no child can be left behind.
            shutil.rmtree(abandon_dir, ignore_errors=True)
        if result is not None:
            if "probe" not in diag:
                diag["probe"] = (
                    "ok (single-claim child)"
                    if "aborted_at" not in result.get("detail", {})
                    else "child self-aborted at its deadline; partial phases reported"
                )
            detail = result.get("detail", {})
            if (
                detail.get("indexed_join_p50_s") is None
                and detail.get("backend") != "cpu"
                and not os.environ.get("BENCH_NO_CPU_MERGE")
            ):
                # The device partial lacks the headline measurement (relay
                # died / deadline hit before the indexed join). Run the CPU
                # bench in-process and attach the device partial: the driver
                # still gets a complete, honest metric, and the on-device
                # evidence rides along instead of being the whole story.
                diag["probe"] = (
                    str(diag.get("probe", ""))
                    + "; device partial lacks headline -> CPU merge run"
                )
                print(json.dumps({"warning": diag["probe"]}), file=sys.stderr)
                tpu_partial = detail
                # Bank the salvage FIRST: the merge run can die in ways no
                # except catches (OOM kill holding 8M-row datagen arrays, an
                # outer supervisor timeout). The driver tail-parses the LAST
                # line, so a completed merge simply supersedes this record;
                # a hard death leaves the device partial as the artifact
                # instead of nothing.
                print(json.dumps({"bench_detail": detail}))
                print(
                    json.dumps(
                        {
                            "metric": result.get("metric", "")[:80],
                            "value": result.get("value"),
                            "unit": "s",
                            "vs_baseline": result.get("vs_baseline"),
                            "detail": {
                                "backend": detail.get("backend"),
                                "rows": detail.get("rows"),
                                "build_s": detail.get("build_s"),
                                "indexed_join_p50_s": detail.get("indexed_join_p50_s"),
                            },
                        },
                        separators=(",", ":"),
                    ),
                    flush=True,
                )
                try:
                    # Best-effort END TO END: never trade the device partial
                    # for a CPU crash, including jax import or platform-
                    # selection failures. The merge gets its own deadline so
                    # it cannot outrun an outer supervisor budget.
                    import jax

                    jax.config.update("jax_platforms", "cpu")
                    _enable_compile_cache()
                    merge_budget = int(os.environ.get("BENCH_CPU_MERGE_TIMEOUT_S", 900))
                    merged = run_bench(deadline=_now() + merge_budget)
                    merged["detail"]["tpu_partial"] = tpu_partial
                    merged["detail"]["backend"] = "cpu+tpu-partial"
                    result = merged
                except Exception as e:
                    diag["cpu_merge_error"] = f"{type(e).__name__}: {e}"[:300]
            _finish(result, diag, t_setup0)
            return
        diag["probe"] = f"tpu child failed ({state}); benching on cpu"
        print(json.dumps({"warning": diag["probe"]}), file=sys.stderr)
    else:
        diag = {"probe": "skipped (BENCH_FORCE_CPU)"}

    import jax

    jax.config.update("jax_platforms", "cpu")
    _enable_compile_cache()
    result = run_bench()
    _finish(result, diag, t_setup0)


def _finish(result: dict, diag: dict, t_setup0: float) -> None:
    detail = result.get("detail", {})
    if not (os.environ.get("BENCH_SKIP_MESH") or os.environ.get("BENCH_SKIP_DIST")):
        detail["mesh"] = _run_mesh_subprocess()
        # The encoded-device section's mesh half (exchange bytes_moved on vs
        # off) is measured inside the multi-device child; fold it in next to
        # the section's own H2D half so `bench_detail.encoded_device` carries
        # the whole story.
        enc_dev = (
            detail["mesh"].pop("encoded_device", None)
            if isinstance(detail.get("mesh"), dict)
            else None
        )
        if isinstance(enc_dev, dict):
            detail.setdefault("encoded_device", {}).update(enc_dev)
        # Same fold for the packed-lane section's mesh half.
        pk_mesh = (
            detail["mesh"].pop("packed_codes", None)
            if isinstance(detail.get("mesh"), dict)
            else None
        )
        if isinstance(pk_mesh, dict):
            detail.setdefault("packed_codes", {}).update(
                {f"mesh_{k}": v for k, v in pk_mesh.items()}
            )
    detail["backend_probe"] = diag
    detail["setup_s"] = round(_now() - t_setup0, 1)
    # Full detail on its own line; the compact machine-readable record LAST
    # (≤200 bytes) so the driver's tail-parse never truncates mid-JSON.
    print(json.dumps({"bench_detail": detail}))
    compact = {
        "metric": result.get("metric", "")[:80],
        "value": result.get("value"),
        "unit": result.get("unit", "s"),
        "vs_baseline": result.get("vs_baseline"),
        "detail": {
            "backend": detail.get("backend", "unknown"),
            "rows": detail.get("rows"),
            "build_s": detail.get("build_s"),
            "indexed_join_p50_s": detail.get("indexed_join_p50_s"),
        },
    }
    print(json.dumps(compact, separators=(",", ":")))


if __name__ == "__main__":
    main()
