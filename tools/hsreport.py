#!/usr/bin/env python
"""hsreport: render a workload-history store as an operator report.

The engine lands every closed query ledger in on-lake JSONL segments
(`hyperspace_tpu.telemetry.history`, ``HYPERSPACE_HISTORY=1``), keyed by a
stable plan-class fingerprint. This tool is the read side: what an operator
(or the ROADMAP-4 cost model's author) asks of a workload's history.

Usage:
    python tools/hsreport.py HISTORY_DIR [--top 10] [--recent 5]
        [--fingerprint PREFIX] [--compare OTHER_DIR] [--json]

Sections:
- **Top plan classes by total cost** — per fingerprint: query count, names,
  total/p50/p99 wall, bytes decoded, decode files, retries, compiles.
- **Expected-vs-actual drift** — per class: the baseline p50 (everything but
  the newest ``--recent`` queries, compacted checkpoints included) vs the
  recent-window p50 — the "is this class getting slower" view
  (`tools/bench_compare.py --history` gates on exactly this).
- **Stage drift** — the same expected-vs-actual question at STAGE grain
  (per-stage busy walls from the stage ledger, recorded when
  ``HYPERSPACE_STAGE_ATTRIBUTION`` was on): which stage of a drifting
  class actually moved — a decode regression and a probe regression are
  different runbook pages.
- ``--fingerprint PREFIX`` scopes every section to plan classes whose
  fingerprint starts with PREFIX — drill into one class's history.
- **SLO compliance** — lane-labeled ledgers (served queries) judged against
  the ambient ``HYPERSPACE_SLO_*`` objectives via `telemetry.slo.
  compliance_over` — the offline twin of the live monitor.
- **Replica fleet** — when the store was written by a replica fleet
  (``HYPERSPACE_REPLICAS=1``: K server processes landing segments in ONE
  shared history dir, each record stamped with its writer's `replica_id`),
  the fleet-wide totals split per replica: records, attributed wall,
  per-lane SLO compliance (the same `compliance_over` judgment, scoped to
  one replica's traffic), and each replica's top plan classes — is the
  fleet balanced, and is one replica dragging the lane SLO.
- **Hotspots** — compile-storm classes (most XLA compiles) and retry
  hotspots (most io retries): where warm-path latency is going to compile
  or fault churn.
- **Device cost** (`telemetry.device_observatory` fields, recorded when
  ``HYPERSPACE_DEVICE_TIMING`` was on) — device-time hotspots per class
  (device seconds and share of class wall), the pow2 padding tax per class
  (payload vs padded bytes, pad_ratio), and effective transfer bandwidth
  (h2d+d2h bytes over class wall).
- ``--compare OTHER_DIR`` — two stores' per-class baselines flattened and
  diffed with `tools.bench_compare`'s machinery (shared `flatten`/`compare`
  — one comparison semantics across both tools); regressed classes exit 1.

``--json`` emits the whole report as one JSON object for scripting.
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import os
import sys
from typing import Dict, List, Optional, Tuple

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from hyperspace_tpu.telemetry import history as _history  # noqa: E402
from hyperspace_tpu.telemetry import slo as _slo  # noqa: E402


def _load_bench_compare():
    """The sibling module, loaded by path (tools/ is not a package)."""
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)), "bench_compare.py")
    spec = importlib.util.spec_from_file_location("hs_bench_compare", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def load_dir(dir_path: str) -> Tuple[Dict[str, list], Dict[str, list]]:
    """(raw ledger records by fingerprint, checkpoint records by
    fingerprint), ledgers time-ordered — the store's own grouping."""
    return _history.split_records(_history.iter_records(dir_path))


def fold_dir(dir_path: str) -> Dict[str, dict]:
    """Per-fingerprint baseline summaries of everything in the store."""
    return {
        fp: bl.summary()
        for fp, bl in _history.fold_baselines(_history.iter_records(dir_path)).items()
    }


def drift(
    raw: Dict[str, list], checkpoints: Dict[str, list], recent_k: int
) -> List[dict]:
    """Expected-vs-actual per class: baseline p50 (all but the newest
    `recent_k` ledgers + checkpoints) vs the recent-window p50 — the shared
    `history.recent_vs_baseline` computation (what `bench_compare
    --history` gates), shown for EVERY class with any recent signal."""
    out = _history.recent_vs_baseline(raw, checkpoints, recent_k)
    out.sort(key=lambda d: -(d["ratio"] or 0.0))
    return out


def build_report(
    dir_path: str, top: int, recent_k: int, fingerprint: Optional[str] = None
) -> dict:
    raw, checkpoints = load_dir(dir_path)
    if fingerprint:
        raw = {fp: v for fp, v in raw.items() if fp.startswith(fingerprint)}
        checkpoints = {
            fp: v for fp, v in checkpoints.items() if fp.startswith(fingerprint)
        }
    baselines = {
        fp: bl.summary()
        for fp, bl in _history.fold_baselines(
            rec
            for recs in (raw, checkpoints)
            for fp_recs in recs.values()
            for rec in fp_recs
        ).items()
    }
    classes = sorted(
        baselines.items(), key=lambda kv: -(kv[1].get("wall_total_s") or 0.0)
    )
    all_ledgers = [r["ledger"] for recs in raw.values() for r in recs]
    report = {
        "dir": os.path.abspath(dir_path),
        "fingerprints": len(baselines),
        "ledger_records": sum(len(v) for v in raw.values()),
        "checkpoint_records": sum(len(v) for v in checkpoints.values()),
        "total_wall_s": round(
            sum(s.get("wall_total_s") or 0.0 for s in baselines.values()), 3
        ),
        "top_classes": [
            dict(fingerprint=fp, **summary) for fp, summary in classes[:top]
        ],
        "drift": drift(raw, checkpoints, recent_k)[:top],
        "stage_drift": _stage_drift(raw, checkpoints, recent_k, top),
        "slo": _slo.compliance_over(all_ledgers),
        "replicas": _replica_fleet(raw, top),
        "compile_hotspots": [
            {
                "fingerprint": fp,
                "names": s.get("names"),
                "xla_compiles": s.get("xla_compiles", 0),
                "n": s.get("n"),
            }
            for fp, s in sorted(
                baselines.items(), key=lambda kv: -kv[1].get("xla_compiles", 0)
            )[:top]
            if s.get("xla_compiles")
        ],
        "retry_hotspots": [
            {
                "fingerprint": fp,
                "names": s.get("names"),
                "io_retries": s.get("io_retries", 0),
                "n": s.get("n"),
            }
            for fp, s in sorted(
                baselines.items(), key=lambda kv: -kv[1].get("io_retries", 0)
            )[:top]
            if s.get("io_retries")
        ],
        "device_hotspots": _device_hotspots(baselines, top),
        "pad_tax": _pad_tax(baselines, top),
        "transfer_bandwidth": _transfer_bandwidth(baselines, top),
        "code_staging": _code_staging(baselines, top),
        "planner": _planner_table(raw, dir_path, top),
    }
    if fingerprint:
        report["fingerprint_filter"] = fingerprint
    return report


def _stage_drift(
    raw: Dict[str, list], checkpoints: Dict[str, list], recent_k: int, top: int
) -> List[dict]:
    """Expected-vs-actual at STAGE grain: per (class, stage) — the mean
    per-query stage busy wall of the newest `recent_k` ledgers vs the class
    baseline (compacted checkpoint stage accumulators + every older raw
    ledger). Same window split as `drift`, but localized: when a class's
    whole-wall drift row fires, this table says WHICH stage moved. Stage
    vectors ride ledgers only when ``HYPERSPACE_STAGE_ATTRIBUTION`` was on;
    classes/stages without both a recent and a baseline signal are
    omitted. Worst ratio first."""
    rows = []
    for fp in sorted(set(raw) | set(checkpoints)):
        ledgers = raw.get(fp, [])
        recent = [
            r["ledger"]["stages"]
            for r in ledgers[-recent_k:]
            if isinstance(r["ledger"].get("stages"), dict)
        ]
        if not recent:
            continue
        base: Dict[str, list] = {}  # stage -> [wall_sum, n]
        for rec in checkpoints.get(fp, ()):
            stages = rec.get("stages")
            if not isinstance(stages, dict):
                continue
            for st, vec in stages.items():
                if not isinstance(vec, dict):
                    continue
                acc = base.setdefault(st, [0.0, 0])
                acc[0] += float(vec.get("wall_s") or 0.0)
                n = vec.get("n")
                acc[1] += n if isinstance(n, int) and n > 0 else 1
        for rec in ledgers[:-recent_k]:
            stages = rec["ledger"].get("stages")
            if not isinstance(stages, dict):
                continue
            for st, vec in stages.items():
                if isinstance(vec, dict) and vec.get("wall_s"):
                    acc = base.setdefault(st, [0.0, 0])
                    acc[0] += float(vec["wall_s"])
                    acc[1] += 1
        for st in sorted({s for stages in recent for s in stages}):
            walls = [
                float(stages[st].get("wall_s") or 0.0)
                for stages in recent
                if isinstance(stages.get(st), dict)
            ]
            if not walls:
                continue
            bw, bn = base.get(st, (0.0, 0))
            if not bn or bw <= 0:
                continue
            expected = bw / bn
            actual = sum(walls) / len(walls)
            rows.append(
                {
                    "fingerprint": fp,
                    "stage": st,
                    "baseline_n": bn,
                    "expected_wall_s": round(expected, 6),
                    "recent_n": len(walls),
                    "actual_wall_s": round(actual, 6),
                    "ratio": round(actual / expected, 3),
                }
            )
    rows.sort(key=lambda r: -r["ratio"])
    return rows[:top]


def _replica_fleet(raw: Dict[str, list], top: int) -> Optional[dict]:
    """Per-replica vs fleet split of a shared history dir. Records are
    attributed by the segment-record envelope stamp (every record lands
    with its writer's `replica_id`; older ledgers may carry it only inside
    the ledger dict — both are read). None when no record is stamped (a
    pre-fleet store) so pre-existing report consumers see an unchanged
    report shape."""
    by_replica: Dict[str, dict] = {}
    stamped = 0
    for fp, recs in raw.items():
        for r in recs:
            led = r.get("ledger") or {}
            rid = r.get("replica_id") or led.get("replica_id")
            if not rid:
                continue
            stamped += 1
            st = by_replica.setdefault(
                rid, {"records": 0, "wall_s": 0.0, "ledgers": [], "classes": {}}
            )
            st["records"] += 1
            st["wall_s"] += float(led.get("wall_s") or 0.0)
            st["ledgers"].append(led)
            cl = st["classes"]
            cl[fp] = cl.get(fp, 0.0) + float(led.get("wall_s") or 0.0)
    if not stamped:
        return None
    replicas = {}
    for rid, st in sorted(by_replica.items()):
        top_classes = sorted(st["classes"].items(), key=lambda kv: -kv[1])[:top]
        replicas[rid] = {
            "records": st["records"],
            "wall_s": round(st["wall_s"], 3),
            "slo": _slo.compliance_over(st["ledgers"]),
            "top_classes": [
                {"fingerprint": fp, "wall_s": round(w, 3)} for fp, w in top_classes
            ],
        }
    all_ledgers = [led for st in by_replica.values() for led in st["ledgers"]]
    return {
        "replicas": replicas,
        "fleet": {
            "size": len(by_replica),
            "records": stamped,
            "wall_s": round(sum(st["wall_s"] for st in by_replica.values()), 3),
            "slo": _slo.compliance_over(all_ledgers),
        },
    }


def _device_hotspots(baselines: Dict[str, dict], top: int) -> List[dict]:
    """Classes by attributed device seconds (sampled execute probes). The
    share column is device over class wall: a class at ~1.0 is
    device-bound; one near 0 spends its wall on host decode/plan."""
    rows = []
    for fp, s in baselines.items():
        dev = s.get("device_time_s")
        if not dev:
            continue
        wall = s.get("wall_total_s") or 0.0
        rows.append(
            {
                "fingerprint": fp,
                "names": s.get("names"),
                "n": s.get("n"),
                "device_time_s": round(dev, 6),
                "device_share": round(dev / wall, 3) if wall else None,
            }
        )
    rows.sort(key=lambda r: -r["device_time_s"])
    return rows[:top]


def _pad_tax(baselines: Dict[str, dict], top: int) -> List[dict]:
    """Classes by pow2 padding tax: padded (wasted) bytes staged next to
    payload bytes, worst pad_ratio first among the biggest wasters."""
    rows = []
    for fp, s in baselines.items():
        payload = s.get("pad_bytes_payload", 0)
        padded = s.get("pad_bytes_padded", 0)
        if not (payload or padded):
            continue
        rows.append(
            {
                "fingerprint": fp,
                "names": s.get("names"),
                "n": s.get("n"),
                "pad_bytes_payload": payload,
                "pad_bytes_padded": padded,
                "pad_ratio": round(padded / (payload + padded), 4),
            }
        )
    rows.sort(key=lambda r: -r["pad_bytes_padded"])
    return rows[:top]


def _transfer_bandwidth(baselines: Dict[str, dict], top: int) -> List[dict]:
    """Effective transfer bandwidth per class: (h2d + d2h bytes) over the
    class's total wall. This is NOT link peak — it answers "how much of
    this class's wall is moving bytes", comparable across classes."""
    rows = []
    for fp, s in baselines.items():
        moved = (s.get("device_upload_bytes", 0) or 0) + (s.get("d2h_bytes", 0) or 0)
        if not moved:
            continue
        wall = s.get("wall_total_s") or 0.0
        row = {
            "fingerprint": fp,
            "names": s.get("names"),
            "n": s.get("n"),
            "bytes_moved": moved,
            "effective_gbps": round(moved / wall / 1e9, 4) if wall else None,
        }
        packed = s.get("device_code_bytes_packed", 0)
        if packed:
            # The slice of the moved bytes that crossed as bit-packed
            # sub-byte words (HYPERSPACE_PACKED_CODES).
            row["bytes_packed"] = packed
        rows.append(row)
    rows.sort(key=lambda r: -r["bytes_moved"])
    return rows[:top]


def _code_staging(baselines: Dict[str, dict], top: int) -> List[dict]:
    """Encoded-vs-flat device staging per class: what the lanes WOULD have
    cost as flat int32 codes vs the narrow bytes actually moved
    (``device_code_bytes_flat`` / ``device_code_bytes_staged``, recorded by
    the encoded-staging ledger under ``HYPERSPACE_ENCODED_DEVICE``). A class
    with no rows here staged nothing in code space — flat fallback or
    numeric-only keys. ``code_bytes_packed`` is the BIT-PACKED sub-byte tier
    of the staged bytes (``HYPERSPACE_PACKED_CODES``): for it the report adds
    the average bits charged per code and the pack ratio vs the int8 narrow
    floor (int8 would charge flat/4 bytes — one byte per code)."""
    rows = []
    for fp, s in baselines.items():
        flat = s.get("device_code_bytes_flat", 0)
        staged = s.get("device_code_bytes_staged", 0)
        if not (flat or staged):
            continue
        row = {
            "fingerprint": fp,
            "names": s.get("names"),
            "n": s.get("n"),
            "code_bytes_flat": flat,
            "code_bytes_staged": staged,
            "saved_ratio": round(1.0 - staged / flat, 4) if flat else None,
        }
        packed = s.get("device_code_bytes_packed", 0)
        if packed:
            n_codes = flat // 4  # flat charges int32 — 4 bytes per code
            row["code_bytes_packed"] = packed
            row["bits_per_code"] = (
                round(packed * 8 / n_codes, 2) if n_codes else None
            )
            row["packed_vs_int8_x"] = round(n_codes / packed, 2) if packed else None
        rows.append(row)
    rows.sort(key=lambda r: -(r["code_bytes_flat"] - r["code_bytes_staged"]))
    return rows[:top]


def _planner_table(raw, dir_path: str, top: int) -> list:
    """Adaptive-planner decision drift: per (fingerprint, knob, arm) — how
    many decided queries ran under it, mean measured wall, mean predicted
    attributable cost, and the drift ratio between them. Joined from two
    feeds that cover each other's gaps: the ``planner`` dicts accounted
    ledger records carry (annotated at ledger close) and the planner outcome
    store's own sidecar segments (``<dir>/planner/*.jsonl`` — present even
    for queries that ran without accounting). Worst drift first: the top
    rows are the classes where the cost model most misprices reality."""
    agg: dict = {}

    def fold(fp, knob, arm, wall, pred, source):
        st = agg.setdefault((fp, knob, str(arm)), [0, 0.0, 0.0, set()])
        st[0] += 1
        st[1] += float(wall or 0.0)
        st[2] += float(pred or 0.0)
        if source:
            st[3].add(source)

    for fp, recs in raw.items():
        for r in recs:
            led = r.get("ledger") or {}
            p = led.get("planner")
            if not isinstance(p, dict):
                continue
            wall = p.get("actual_wall_s") or led.get("wall_s") or 0.0
            for knob, d in p.items():
                if isinstance(d, dict) and "arm" in d:
                    fold(fp, knob, d["arm"], wall, d.get("predicted_s"), d.get("source"))
    pdir = os.path.join(dir_path, "planner")
    if os.path.isdir(pdir):
        for name in sorted(os.listdir(pdir)):
            if not name.endswith(".jsonl"):
                continue
            for rec in _history.iter_file_records(os.path.join(pdir, name)):
                if rec.get("kind") != "planner_outcome":
                    continue
                fp, outs = rec.get("fingerprint"), rec.get("outcomes")
                if not fp or not isinstance(outs, dict):
                    continue
                for knob, o in outs.items():
                    if isinstance(o, dict) and "arm" in o:
                        fold(fp, knob, o["arm"], o.get("wall_s"), o.get("predicted_s"), "store")
    rows = []
    for (fp, knob, arm), (n, ws, ps, sources) in agg.items():
        mean_w, mean_p = (ws / n, ps / n) if n else (0.0, 0.0)
        rows.append(
            {
                "fingerprint": fp,
                "knob": knob,
                "arm": arm,
                "n": n,
                "mean_wall_s": round(mean_w, 6),
                "mean_predicted_s": round(mean_p, 6),
                "drift_x": round(mean_w / mean_p, 2) if mean_p > 0 else None,
                "sources": sorted(sources),
            }
        )
    rows.sort(key=lambda r: -(r["drift_x"] or 0.0))
    return rows[:top]


def _fmt_s(v: Optional[float]) -> str:
    if v is None:
        return "-"
    return f"{v * 1000:.1f}ms" if v < 1 else f"{v:.3f}s"


def render(report: dict) -> str:
    lines = [
        f"workload history: {report['dir']}"
        + (
            f"  (classes matching {report['fingerprint_filter']}*)"
            if report.get("fingerprint_filter")
            else ""
        ),
        f"  {report['ledger_records']} ledgers + "
        f"{report['checkpoint_records']} checkpoints across "
        f"{report['fingerprints']} plan classes; "
        f"total attributed wall {report['total_wall_s']:.3f}s",
        "",
        "top plan classes by total cost:",
    ]
    for c in report["top_classes"]:
        names = ",".join(c.get("names") or []) or "?"
        lines.append(
            f"  {c['fingerprint']}  n={c['n']:<5} total={_fmt_s(c['wall_total_s'])}"
            f"  p50={_fmt_s(c.get('wall_p50_s'))} p99={_fmt_s(c.get('wall_p99_s'))}"
            f"  decoded={c.get('bytes_decoded', 0)}B"
            f"  [{names}]"
        )
    if report["drift"]:
        lines += ["", "expected vs actual (recent window vs class baseline):"]
        for d in report["drift"]:
            names = ",".join(d.get("names") or []) or "?"
            lines.append(
                f"  {d['fingerprint']}  expected={_fmt_s(d['expected_p50_s'])}"
                f" actual={_fmt_s(d['actual_p50_s'])} (x{d['ratio']})"
                f"  baseline_n={d['baseline_n']}  [{names}]"
            )
    if report.get("stage_drift"):
        lines += ["", "stage drift (recent stage busy-wall vs class baseline):"]
        for d in report["stage_drift"]:
            lines.append(
                f"  {d['fingerprint']}  {d['stage']:<10}"
                f" expected={_fmt_s(d['expected_wall_s'])}"
                f" actual={_fmt_s(d['actual_wall_s'])} (x{d['ratio']})"
                f"  baseline_n={d['baseline_n']} recent_n={d['recent_n']}"
            )
    if report["slo"]:
        lines += ["", "SLO compliance (recorded serving traffic):"]
        for lane, s in report["slo"].items():
            verdict = "MET" if s["met"] else ("MISSED" if s["met"] is not None else "-")
            lines.append(
                f"  {lane}: {s['total']} queries, {s['violations']} over "
                f"{s['objective_ms']:g}ms, compliance="
                f"{s['compliance'] if s['compliance'] is not None else '-'}"
                f" (target {s['target']:.2%}) {verdict}"
            )
    if report.get("replicas"):
        fleet = report["replicas"]["fleet"]
        lines += [
            "",
            f"replica fleet: {fleet['size']} replica(s), {fleet['records']} "
            f"records, total wall {fleet['wall_s']:.3f}s",
        ]
        for lane, s in (fleet.get("slo") or {}).items():
            verdict = "MET" if s["met"] else ("MISSED" if s["met"] is not None else "-")
            lines.append(
                f"  fleet {lane}: {s['total']} queries, {s['violations']} over "
                f"{s['objective_ms']:g}ms, compliance="
                f"{s['compliance'] if s['compliance'] is not None else '-'} {verdict}"
            )
        for rid, st in report["replicas"]["replicas"].items():
            lines.append(
                f"  {rid}: {st['records']} records, wall {st['wall_s']:.3f}s"
            )
            for lane, s in (st.get("slo") or {}).items():
                verdict = (
                    "MET" if s["met"] else ("MISSED" if s["met"] is not None else "-")
                )
                lines.append(
                    f"    {lane}: {s['total']} queries, compliance="
                    f"{s['compliance'] if s['compliance'] is not None else '-'} "
                    f"{verdict}"
                )
            for c in st.get("top_classes") or []:
                lines.append(
                    f"    class {c['fingerprint']}  wall={c['wall_s']:.3f}s"
                )
    if report["compile_hotspots"]:
        lines += ["", "compile-storm hotspots (XLA compiles per class):"]
        for h in report["compile_hotspots"]:
            lines.append(
                f"  {h['fingerprint']}  compiles={h['xla_compiles']} over "
                f"{h['n']} queries  [{','.join(h.get('names') or [])}]"
            )
    if report["retry_hotspots"]:
        lines += ["", "io-retry hotspots (transient-fault churn per class):"]
        for h in report["retry_hotspots"]:
            lines.append(
                f"  {h['fingerprint']}  retries={h['io_retries']} over "
                f"{h['n']} queries  [{','.join(h.get('names') or [])}]"
            )
    if report.get("device_hotspots"):
        lines += ["", "device-time hotspots (sampled execute probes per class):"]
        for h in report["device_hotspots"]:
            share = (
                f" ({h['device_share']:.0%} of wall)"
                if h.get("device_share") is not None
                else ""
            )
            lines.append(
                f"  {h['fingerprint']}  device={_fmt_s(h['device_time_s'])}{share}"
                f" over {h['n']} queries  [{','.join(h.get('names') or [])}]"
            )
    if report.get("pad_tax"):
        lines += ["", "pow2 padding tax (wasted staged bytes per class):"]
        for h in report["pad_tax"]:
            lines.append(
                f"  {h['fingerprint']}  payload={h['pad_bytes_payload']}B"
                f" padded={h['pad_bytes_padded']}B"
                f" pad_ratio={h['pad_ratio']}"
                f"  [{','.join(h.get('names') or [])}]"
            )
    if report.get("transfer_bandwidth"):
        lines += ["", "effective transfer bandwidth (h2d+d2h over class wall):"]
        for h in report["transfer_bandwidth"]:
            gbps = h.get("effective_gbps")
            packed = (
                f" packed={h['bytes_packed']}B" if h.get("bytes_packed") else ""
            )
            lines.append(
                f"  {h['fingerprint']}  moved={h['bytes_moved']}B{packed}"
                f"  {gbps if gbps is not None else '-'} GB/s"
                f"  [{','.join(h.get('names') or [])}]"
            )
    if report.get("code_staging"):
        lines += ["", "device code staging (encoded vs flat H2D bytes):"]
        for h in report["code_staging"]:
            saved = h.get("saved_ratio")
            saved_str = f" saved={saved:.0%}" if saved is not None else ""
            packed_str = ""
            if h.get("code_bytes_packed"):
                packed_str = (
                    f" packed={h['code_bytes_packed']}B"
                    f" ({h['bits_per_code']}b/code,"
                    f" {h['packed_vs_int8_x']}x vs int8)"
                )
            lines.append(
                f"  {h['fingerprint']}  flat={h['code_bytes_flat']}B"
                f" staged={h['code_bytes_staged']}B{saved_str}{packed_str}"
                f"  [{','.join(h.get('names') or [])}]"
            )
    if report.get("planner"):
        lines += [
            "",
            "planner decisions (per class/knob/arm — worst predicted-vs-actual drift first):",
        ]
        for h in report["planner"]:
            drift = h.get("drift_x")
            lines.append(
                f"  {h['fingerprint']}  {h['knob']}={h['arm']}  n={h['n']}"
                f"  wall={_fmt_s(h['mean_wall_s'])}"
                f"  predicted={_fmt_s(h['mean_predicted_s'])}"
                f"  drift_x={drift if drift is not None else '-'}"
                f"  [{','.join(h.get('sources') or [])}]"
            )
    return "\n".join(lines)


#: Per-class leaves the --compare gate judges: PER-QUERY latency stats only.
#: Cumulative/statistical leaves also end in ``_s`` but must never gate —
#: ``wall_total_s`` grows with recorded traffic (a store that simply saw 5x
#: the queries is not 5x slower), ``wall_max_s`` is one outlier, and
#: ``wall_std_s`` is not a latency at all.
GATED_LEAVES = ("wall_p50_s", "wall_p99_s", "wall_mean_s")


def compare_dirs(dir_a: str, dir_b: str, threshold: float) -> int:
    """Diff two stores' per-class baselines via `bench_compare`'s shared
    flatten/compare machinery. Returns 1 when a shared class's PER-QUERY
    timing (`GATED_LEAVES`) regressed past `threshold`, else 0."""
    bc = _load_bench_compare()

    def _gateable(flat):
        return {
            k: v
            for k, v in flat.items()
            if not bc.is_timing(k) or k.rsplit(".", 1)[-1] in GATED_LEAVES
        }

    flat_a = _gateable(bc.flatten(fold_dir(dir_a)))
    flat_b = _gateable(bc.flatten(fold_dir(dir_b)))
    rows, regressions = bc.compare(flat_a, flat_b, threshold, 0.01, [])
    print(f"hsreport compare: {dir_a} -> {dir_b} ({len(rows)} shared leaves)")
    for key, b, c, delta, ratio, gated in rows:
        if not bc.is_timing(key):
            continue
        mark = "  REGRESSION" if gated else ""
        print(f"  {key}: {b:.6g} -> {c:.6g}  ({delta:+.6g}, x{ratio:.3f}){mark}")
    if regressions:
        print(f"FAIL: {len(regressions)} class timing(s) regressed", file=sys.stderr)
        return 1
    print("OK: no class timing regressions")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("history_dir", help="workload history directory")
    ap.add_argument("--top", type=int, default=10, help="rows per section")
    ap.add_argument(
        "--recent", type=int, default=5, help="recent-window size for drift"
    )
    ap.add_argument(
        "--fingerprint",
        default=None,
        metavar="PREFIX",
        help="only plan classes whose fingerprint starts with PREFIX",
    )
    ap.add_argument(
        "--compare",
        default=None,
        metavar="DIR",
        help="second history dir: diff per-class baselines (exit 1 on regression)",
    )
    ap.add_argument(
        "--threshold",
        type=float,
        default=0.25,
        help="allowed fractional regression for --compare (default 0.25)",
    )
    ap.add_argument("--json", action="store_true", help="emit the report as JSON")
    args = ap.parse_args(argv)

    if not os.path.isdir(args.history_dir):
        print(f"hsreport: not a directory: {args.history_dir}", file=sys.stderr)
        return 2
    report = build_report(
        args.history_dir, args.top, args.recent, fingerprint=args.fingerprint
    )
    if args.json:
        print(json.dumps(report, indent=2, default=str))
    else:
        print(render(report))
    if args.compare:
        return compare_dirs(args.history_dir, args.compare, args.threshold)
    return 0


if __name__ == "__main__":
    sys.exit(main())
