#!/usr/bin/env python
"""hstimeline: emit a query's span tree as a Chrome-trace/Perfetto timeline.

The JSONL span export (``HYPERSPACE_TRACE_FILE``) records every span of
every traced query — one JSON object per span, whole traces appended
atomically. This tool joins one query's spans back into a causal timeline:
one lane per stage (the synthesized ``<kind>:<stage>`` spans), one lane for
operator spans, one for pool-worker families — the `stage_ledger.
chrome_trace` conversion, loadable in ``chrome://tracing`` or Perfetto's
legacy importer.

Usage:
    python tools/hstimeline.py TRACE_FILE [--query-id ID] [--list]
        [--out PATH]

- With no ``--query-id`` the NEWEST query in the file is converted.
- ``--list`` prints every query id in the file (with span counts) and exits.
- ``--out`` defaults to ``timeline-<query_id>.json`` in the cwd; ``-``
  writes the JSON to stdout.

Live capture needs no tool run at all: set ``HYPERSPACE_TIMELINE_DIR`` and
every root query writes its own ``timeline-<query_id>.json`` at close
(`telemetry.tracing._finalize`).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from hyperspace_tpu.telemetry import stage_ledger as _stage_ledger  # noqa: E402


def load_spans(path: str) -> Dict[str, List[dict]]:
    """Span dicts grouped by query_id, file order preserved (the exporter
    appends whole traces, so file order IS finalize order). Torn/garbled
    lines skip — the history reader's tolerance contract."""
    out: Dict[str, List[dict]] = {}
    with open(path, "r") as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if isinstance(rec, dict) and rec.get("query_id"):
                out.setdefault(str(rec["query_id"]), []).append(rec)
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Convert a hyperspace span-export JSONL into Chrome-trace JSON"
    )
    ap.add_argument("trace_file", help="HYPERSPACE_TRACE_FILE JSONL export")
    ap.add_argument(
        "--query-id", default=None, help="query to convert (default: newest)"
    )
    ap.add_argument(
        "--list", action="store_true", help="list query ids in the file and exit"
    )
    ap.add_argument(
        "--out",
        default=None,
        help="output path (default timeline-<query_id>.json; '-' for stdout)",
    )
    args = ap.parse_args(argv)

    try:
        by_query = load_spans(args.trace_file)
    except OSError as e:
        print(f"hstimeline: cannot read {args.trace_file}: {e}", file=sys.stderr)
        return 2
    if not by_query:
        print(f"hstimeline: no spans in {args.trace_file}", file=sys.stderr)
        return 2

    if args.list:
        for qid, spans in by_query.items():
            roots = [s for s in spans if s.get("parent_id") is None]
            name = roots[0].get("name") if roots else "?"
            print(f"{qid}  spans={len(spans)}  root={name}")
        return 0

    qid = args.query_id
    if qid is None:
        qid = next(reversed(by_query))  # newest: last appended trace
    spans = by_query.get(qid)
    if spans is None:
        print(
            f"hstimeline: query_id {qid!r} not in {args.trace_file} "
            f"({len(by_query)} queries; --list to enumerate)",
            file=sys.stderr,
        )
        return 2

    doc = _stage_ledger.chrome_trace(spans)
    lanes = doc.get("otherData", {}).get("lanes", [])
    if args.out == "-":
        json.dump(doc, sys.stdout, default=str)
        sys.stdout.write("\n")
        return 0
    out = args.out or f"timeline-{qid}.json"
    with open(out, "w") as fh:
        json.dump(doc, fh, default=str)
    print(
        f"hstimeline: wrote {out}  query_id={qid} events="
        f"{len(doc['traceEvents'])} lanes={len(lanes)}"
    )
    stage_lanes = [ln for ln in lanes if ln.startswith("stage:")]
    if stage_lanes:
        print(f"  stage lanes: {', '.join(stage_lanes)}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
