#!/usr/bin/env python
"""Compare two bench JSON artifacts and gate on timing regressions.

The BENCH_r01…r05 trajectory has been eyeballed PR over PR; this tool makes
the comparison mechanical: flatten both files to dotted numeric leaves,
report per-metric deltas, and exit non-zero when any TIMING metric regressed
past a configurable threshold.

Usage:
    python tools/bench_compare.py BASELINE.json CANDIDATE.json \
        [--threshold 0.25] [--min-seconds 0.01] [--keys glob ...] [--all] \
        [--history DIR]

``--history DIR`` additionally gates against the WORKLOAD HISTORY STORE
(`hyperspace_tpu.telemetry.history` — the same on-lake segments
`tools/hsreport.py` reads): for every plan-class fingerprint with enough
observed history, the p50 wall of the most recent ``--history-recent``
queries is compared against the class baseline p50 (all older records +
compacted checkpoints) under the same threshold/noise-floor rules. A bench
run that landed its ledgers in the store (``HYPERSPACE_HISTORY=1``) is then
regression-gated per plan class, not just per static bench key. With
``--history`` given, the static BASELINE/CANDIDATE pair becomes optional.

Semantics:
- A metric is a TIMING (lower is better) when its dotted key's leaf ends in
  ``_s`` or ``_seconds`` (``build_s``, ``indexed_cold_s``,
  ``agg_stream_warm_p50_s``, …). Only timings gate the exit code; counters
  and byte totals are reported informationally (with ``--all``).
- Regression = ``candidate > baseline * (1 + threshold)`` AND both values ≥
  ``--min-seconds`` (sub-noise timings never gate — a 2 ms blip is machine
  jitter, not a regression).
- ``--keys`` restricts gating to metrics whose dotted key matches any of the
  given ``fnmatch`` globs (reporting still covers everything shown).
- Bench files wrap their payload as ``{"bench_detail": {...}}`` (the
  driver's tail-parse contract); bare dicts work too.

Exit codes: 0 = no gated regressions, 1 = regressions found, 2 = usage or
unreadable/unparseable input.
"""

from __future__ import annotations

import argparse
import fnmatch
import json
import sys
from typing import Dict, List, Tuple


def flatten(obj, prefix: str = "") -> Dict[str, float]:
    """Dotted-key map of every numeric leaf (bools excluded; lists index by
    position). Non-numeric leaves are dropped — the comparison is about
    measurements, not labels."""
    out: Dict[str, float] = {}
    if isinstance(obj, dict):
        for k, v in obj.items():
            out.update(flatten(v, f"{prefix}{k}."))
    elif isinstance(obj, (list, tuple)):
        for i, v in enumerate(obj):
            out.update(flatten(v, f"{prefix}{i}."))
    elif isinstance(obj, bool):
        pass
    elif isinstance(obj, (int, float)):
        out[prefix[:-1]] = float(obj)
    return out


def load_bench(path: str) -> Dict[str, float]:
    with open(path) as f:
        data = json.load(f)
    if isinstance(data, dict) and "bench_detail" in data:
        data = data["bench_detail"]
    return flatten(data)


def is_timing(key: str) -> bool:
    leaf = key.rsplit(".", 1)[-1]
    return leaf.endswith("_s") or leaf.endswith("_seconds")


def compare(
    base: Dict[str, float],
    cand: Dict[str, float],
    threshold: float,
    min_seconds: float,
    key_globs: List[str],
) -> Tuple[List[tuple], List[tuple]]:
    """(rows, regressions): rows = (key, base, cand, delta, ratio, flag) for
    every shared key; regressions = the gated subset."""
    rows, regressions = [], []
    for key in sorted(set(base) & set(cand)):
        b, c = base[key], cand[key]
        delta = c - b
        ratio = (c / b) if b else (float("inf") if c else 1.0)
        gated = (
            is_timing(key)
            and (not key_globs or any(fnmatch.fnmatch(key, g) for g in key_globs))
            and b >= min_seconds
            and c >= min_seconds
            and c > b * (1.0 + threshold)
        )
        rows.append((key, b, c, delta, ratio, gated))
        if gated:
            regressions.append((key, b, c, delta, ratio))
    return rows, regressions


def _history_rows(
    dir_path: str, threshold: float, min_seconds: float, recent_k: int
):
    """(rows, regressions) per plan-class fingerprint: recent-window p50
    wall vs the class baseline p50 from the history store. Built on the
    store's OWN reader + `recent_vs_baseline` (the exact computation
    `tools/hsreport.py`'s drift table renders), restricted here to credible
    classes: a full recent window and ≥ ANOMALY_MIN_SAMPLES of baseline."""
    import os as _os
    import sys as _sys

    _sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))
    from hyperspace_tpu.telemetry import history as _history

    raw, checkpoints = _history.split_records(_history.iter_records(dir_path))
    rows, regressions = [], []
    for d in _history.recent_vs_baseline(
        raw,
        checkpoints,
        recent_k,
        min_baseline=_history.ANOMALY_MIN_SAMPLES,
        require_full_window=True,
    ):
        base_p50, recent_p50 = d["expected_p50_s"], d["actual_p50_s"]
        names = ",".join(d["names"]) or "?"
        key = f"history.{d['fingerprint']}[{names}].wall_p50_s"
        delta = recent_p50 - base_p50
        ratio = (recent_p50 / base_p50) if base_p50 else float("inf")
        gated = (
            base_p50 >= min_seconds
            and recent_p50 >= min_seconds
            and recent_p50 > base_p50 * (1.0 + threshold)
        )
        rows.append((key, base_p50, recent_p50, delta, ratio, gated))
        if gated:
            regressions.append((key, base_p50, recent_p50, delta, ratio))
    return rows, regressions


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument(
        "baseline", nargs="?", help="baseline bench JSON (e.g. BENCH_r04.json)"
    )
    ap.add_argument(
        "candidate", nargs="?", help="candidate bench JSON (e.g. BENCH_r05.json)"
    )
    ap.add_argument(
        "--threshold",
        type=float,
        default=0.25,
        help="allowed fractional timing regression before failing (default 0.25)",
    )
    ap.add_argument(
        "--min-seconds",
        type=float,
        default=0.01,
        help="timings below this on either side never gate (noise floor, default 0.01)",
    )
    ap.add_argument(
        "--keys",
        nargs="*",
        default=[],
        help="fnmatch globs restricting which timing keys gate (default: all)",
    )
    ap.add_argument(
        "--all",
        action="store_true",
        help="report every shared numeric leaf, not just timings",
    )
    ap.add_argument(
        "--history",
        default=None,
        metavar="DIR",
        help="workload-history dir: additionally gate per-fingerprint "
        "recent p50 wall vs the stored class baseline",
    )
    ap.add_argument(
        "--history-recent",
        type=int,
        default=5,
        help="how many newest ledgers per class form the judged window "
        "(default 5)",
    )
    args = ap.parse_args(argv)
    if not args.history and not (args.baseline and args.candidate):
        ap.error("BASELINE and CANDIDATE are required unless --history is given")
    if (args.baseline is None) != (args.candidate is None):
        # One positional alone (candidate forgotten) must be a loud error:
        # silently skipping the static gate would let CI read green off the
        # history gate alone while believing the bench pair was compared.
        ap.error("BASELINE and CANDIDATE must be given together")

    rows, regressions = [], []
    if args.baseline and args.candidate:
        try:
            base = load_bench(args.baseline)
            cand = load_bench(args.candidate)
        except (OSError, json.JSONDecodeError) as e:
            print(f"bench_compare: cannot read inputs: {e}", file=sys.stderr)
            return 2
        if not base or not cand:
            print("bench_compare: no numeric leaves found", file=sys.stderr)
            return 2

        rows, regressions = compare(
            base, cand, args.threshold, args.min_seconds, args.keys
        )
        shared = [r for r in rows if args.all or is_timing(r[0])]
        print(
            f"bench_compare: {args.baseline} -> {args.candidate}  "
            f"({len(rows)} shared metrics, threshold {args.threshold:+.0%}, "
            f"noise floor {args.min_seconds}s)"
        )
        for key, b, c, delta, ratio, gated in shared:
            mark = "  REGRESSION" if gated else ""
            print(f"  {key}: {b:.6g} -> {c:.6g}  ({delta:+.6g}, x{ratio:.3f}){mark}")
        only_base = sorted(set(base) - set(cand))
        only_cand = sorted(set(cand) - set(base))
        if only_base:
            print(f"  ({len(only_base)} metrics only in baseline)")
        if only_cand:
            print(f"  ({len(only_cand)} metrics only in candidate)")

    if args.history:
        import os

        if not os.path.isdir(args.history):
            # A silently-empty gate is worse than a loud one: a wrong path
            # (or a producing step that moved its output) must fail the CI
            # leg, not print "0 gateable classes" and pass green forever.
            print(
                f"bench_compare: --history is not a directory: {args.history}",
                file=sys.stderr,
            )
            return 2
        try:
            h_rows, h_regs = _history_rows(
                args.history, args.threshold, args.min_seconds, args.history_recent
            )
        except (OSError, ImportError) as e:
            print(f"bench_compare: cannot read history dir: {e}", file=sys.stderr)
            return 2
        print(
            f"bench_compare history gate: {args.history}  "
            f"({len(h_rows)} gateable plan classes, recent window "
            f"{args.history_recent})"
        )
        for key, b, c, delta, ratio, gated in h_rows:
            mark = "  REGRESSION" if gated else ""
            print(f"  {key}: {b:.6g} -> {c:.6g}  ({delta:+.6g}, x{ratio:.3f}){mark}")
        rows.extend(h_rows)
        regressions.extend(h_regs)

    if regressions:
        print(
            f"FAIL: {len(regressions)} timing metric(s) regressed past "
            f"{args.threshold:+.0%}",
            file=sys.stderr,
        )
        return 1
    print("OK: no gated timing regressions")
    return 0


if __name__ == "__main__":
    sys.exit(main())
