#!/usr/bin/env python
"""Compare two bench JSON artifacts and gate on timing regressions.

The BENCH_r01…r05 trajectory has been eyeballed PR over PR; this tool makes
the comparison mechanical: flatten both files to dotted numeric leaves,
report per-metric deltas, and exit non-zero when any TIMING metric regressed
past a configurable threshold.

Usage:
    python tools/bench_compare.py BASELINE.json CANDIDATE.json \
        [--threshold 0.25] [--min-seconds 0.01] [--keys glob ...] [--all]

Semantics:
- A metric is a TIMING (lower is better) when its dotted key's leaf ends in
  ``_s`` or ``_seconds`` (``build_s``, ``indexed_cold_s``,
  ``agg_stream_warm_p50_s``, …). Only timings gate the exit code; counters
  and byte totals are reported informationally (with ``--all``).
- Regression = ``candidate > baseline * (1 + threshold)`` AND both values ≥
  ``--min-seconds`` (sub-noise timings never gate — a 2 ms blip is machine
  jitter, not a regression).
- ``--keys`` restricts gating to metrics whose dotted key matches any of the
  given ``fnmatch`` globs (reporting still covers everything shown).
- Bench files wrap their payload as ``{"bench_detail": {...}}`` (the
  driver's tail-parse contract); bare dicts work too.

Exit codes: 0 = no gated regressions, 1 = regressions found, 2 = usage or
unreadable/unparseable input.
"""

from __future__ import annotations

import argparse
import fnmatch
import json
import sys
from typing import Dict, List, Tuple


def flatten(obj, prefix: str = "") -> Dict[str, float]:
    """Dotted-key map of every numeric leaf (bools excluded; lists index by
    position). Non-numeric leaves are dropped — the comparison is about
    measurements, not labels."""
    out: Dict[str, float] = {}
    if isinstance(obj, dict):
        for k, v in obj.items():
            out.update(flatten(v, f"{prefix}{k}."))
    elif isinstance(obj, (list, tuple)):
        for i, v in enumerate(obj):
            out.update(flatten(v, f"{prefix}{i}."))
    elif isinstance(obj, bool):
        pass
    elif isinstance(obj, (int, float)):
        out[prefix[:-1]] = float(obj)
    return out


def load_bench(path: str) -> Dict[str, float]:
    with open(path) as f:
        data = json.load(f)
    if isinstance(data, dict) and "bench_detail" in data:
        data = data["bench_detail"]
    return flatten(data)


def is_timing(key: str) -> bool:
    leaf = key.rsplit(".", 1)[-1]
    return leaf.endswith("_s") or leaf.endswith("_seconds")


def compare(
    base: Dict[str, float],
    cand: Dict[str, float],
    threshold: float,
    min_seconds: float,
    key_globs: List[str],
) -> Tuple[List[tuple], List[tuple]]:
    """(rows, regressions): rows = (key, base, cand, delta, ratio, flag) for
    every shared key; regressions = the gated subset."""
    rows, regressions = [], []
    for key in sorted(set(base) & set(cand)):
        b, c = base[key], cand[key]
        delta = c - b
        ratio = (c / b) if b else (float("inf") if c else 1.0)
        gated = (
            is_timing(key)
            and (not key_globs or any(fnmatch.fnmatch(key, g) for g in key_globs))
            and b >= min_seconds
            and c >= min_seconds
            and c > b * (1.0 + threshold)
        )
        rows.append((key, b, c, delta, ratio, gated))
        if gated:
            regressions.append((key, b, c, delta, ratio))
    return rows, regressions


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("baseline", help="baseline bench JSON (e.g. BENCH_r04.json)")
    ap.add_argument("candidate", help="candidate bench JSON (e.g. BENCH_r05.json)")
    ap.add_argument(
        "--threshold",
        type=float,
        default=0.25,
        help="allowed fractional timing regression before failing (default 0.25)",
    )
    ap.add_argument(
        "--min-seconds",
        type=float,
        default=0.01,
        help="timings below this on either side never gate (noise floor, default 0.01)",
    )
    ap.add_argument(
        "--keys",
        nargs="*",
        default=[],
        help="fnmatch globs restricting which timing keys gate (default: all)",
    )
    ap.add_argument(
        "--all",
        action="store_true",
        help="report every shared numeric leaf, not just timings",
    )
    args = ap.parse_args(argv)

    try:
        base = load_bench(args.baseline)
        cand = load_bench(args.candidate)
    except (OSError, json.JSONDecodeError) as e:
        print(f"bench_compare: cannot read inputs: {e}", file=sys.stderr)
        return 2
    if not base or not cand:
        print("bench_compare: no numeric leaves found", file=sys.stderr)
        return 2

    rows, regressions = compare(
        base, cand, args.threshold, args.min_seconds, args.keys
    )
    shared = [r for r in rows if args.all or is_timing(r[0])]
    print(
        f"bench_compare: {args.baseline} -> {args.candidate}  "
        f"({len(rows)} shared metrics, threshold {args.threshold:+.0%}, "
        f"noise floor {args.min_seconds}s)"
    )
    for key, b, c, delta, ratio, gated in shared:
        mark = "  REGRESSION" if gated else ""
        print(f"  {key}: {b:.6g} -> {c:.6g}  ({delta:+.6g}, x{ratio:.3f}){mark}")
    only_base = sorted(set(base) - set(cand))
    only_cand = sorted(set(cand) - set(base))
    if only_base:
        print(f"  ({len(only_base)} metrics only in baseline)")
    if only_cand:
        print(f"  ({len(only_cand)} metrics only in candidate)")
    if regressions:
        print(
            f"FAIL: {len(regressions)} timing metric(s) regressed past "
            f"{args.threshold:+.0%}",
            file=sys.stderr,
        )
        return 1
    print("OK: no gated timing regressions")
    return 0


if __name__ == "__main__":
    sys.exit(main())
